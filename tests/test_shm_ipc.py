"""Round-21 shared-memory columnar IPC plane: the SPSC ring protocol's
properties (wraparound, slot reuse, loud backpressure, torn-producer
tombstones), the vectorized trace sampler against its scalar oracle,
per-row conn tagging through ``submit_batch``, the deterministic shm
soak's byte-identical replay, and (slow) the real multi-process
one-store topology including a kill -9 worker crash."""

import os
import signal
import time

import numpy as np
import pytest

from hermes_tpu.config import HermesConfig
from hermes_tpu.kvs import KVS
from hermes_tpu.serving import wire
from hermes_tpu.serving.ipc import (CONN_BITS, OneStoreServer, StoreOwner,
                                    conn_local, conn_worker, pack_conn,
                                    create_ring_pair, req_ring_fields,
                                    run_shm_soak)
from hermes_tpu.serving.server import (ColumnarFrontend, ServingConfig,
                                       VirtualClock, verify_columnar)
from hermes_tpu.transport.shm import ShmBackpressure, SpscColumnRing


def _ring(nslots=4, rows=8):
    return SpscColumnRing.create(
        nslots, rows, (("a", "<i8", 0), ("m", "u1", 16)))


def _cfg(**over):
    kw = dict(n_replicas=3, n_keys=64, n_sessions=4, replay_slots=6,
              ops_per_session=96, value_words=6)
    kw.update(over)
    return HermesConfig(**kw)


def _scfg(**over):
    kw = dict(tenant_rate_per_s=1e9, tenant_burst=1e9,
              tenant_quota=1 << 20, queue_cap=4096, round_us=1000)
    kw.update(over)
    return ServingConfig(**kw)


# -- ring protocol properties -------------------------------------------------


def test_ring_wraparound_and_slot_reuse():
    """Producer/consumer chase each other over many laps: every batch
    arrives intact, in order, through reused slots."""
    r = _ring(nslots=3, rows=4)
    try:
        expect = 0
        for batch in range(40):  # 40 batches through 3 slots
            slot = r.try_claim()
            assert slot is not None
            n = 1 + batch % 4
            slot.cols["a"][:n] = np.arange(batch * 10, batch * 10 + n)
            slot.cols["m"][:n] = batch % 251
            r.commit(n)
            got = r.poll()
            assert got is not None and got.count == n
            assert got.cols["a"][:n].tolist() == list(
                range(batch * 10, batch * 10 + n))
            assert (got.cols["m"][:n] == batch % 251).all()
            r.ack()
            expect += n
        assert r.produced == r.consumed == 40
    finally:
        r.close()


def test_ring_full_is_loud_not_silent():
    """A full ring: try_claim says None, claim_wait raises
    ShmBackpressure once the deadline passes — never a drop, never an
    unbounded block."""
    r = _ring(nslots=2, rows=4)
    try:
        for _ in range(2):
            s = r.try_claim()
            assert s is not None
            r.commit(1)
        assert r.try_claim() is None      # consumer owns every slot
        t0 = time.monotonic()
        with pytest.raises(ShmBackpressure, match="full"):
            r.claim_wait(timeout_s=0.05)
        assert time.monotonic() - t0 < 2.0
        # draining one slot frees exactly one claim
        assert r.poll() is not None
        r.ack()
        assert r.try_claim() is not None
    finally:
        r.close()


def test_ring_torn_producer_tombstone():
    """A claim with no commit (a producer crash) is visible as a torn
    slot: the consumer never surfaces the half-written data, and
    ``torn()`` gives the owner its tombstone signal."""
    r = _ring(nslots=2, rows=4)
    try:
        slot = r.try_claim()
        slot.cols["a"][:2] = (7, 8)   # mid-write ...
        assert r.poll() is None       # ... never visible to the consumer
        assert r.torn()               # ... and flagged as torn
        r.commit(2)
        assert not r.torn()           # published: tombstone cleared
        got = r.poll()
        assert got is not None and got.count == 2
        r.ack()
    finally:
        r.close()


def test_ring_deferred_ack_gathers_multiple_slots():
    """poll() advances without releasing: a consumer may hold views of
    several ready slots (the owner's merge) before acking them FIFO."""
    r = _ring(nslots=4, rows=2)
    try:
        for i in range(3):
            s = r.try_claim()
            s.cols["a"][:1] = i
            r.commit(1)
        views = [r.poll() for _ in range(3)]
        assert [int(v.cols["a"][0]) for v in views] == [0, 1, 2]
        assert r.poll() is None
        assert r.pending_ack() == 3
        assert r.ack(2) == 2          # partial FIFO release
        assert r.pending_ack() == 1
        assert r.ack() == 1
        assert r.consumed == 3
    finally:
        r.close()


def test_ring_attach_shares_the_creator_mapping():
    """attach() by spec maps the same memory (in-process here; the
    slow tests cover real child processes)."""
    r = _ring(nslots=2, rows=4)
    try:
        other = SpscColumnRing.attach(r.spec)
        try:
            s = r.try_claim()
            s.cols["a"][:3] = (5, 6, 7)
            r.commit(3)
            got = other.poll()
            assert got is not None and got.count == 3
            assert got.cols["a"][:3].tolist() == [5, 6, 7]
            other.ack()
            assert r.try_claim() is not None  # ack visible to creator
        finally:
            other.close()
    finally:
        r.close()


# -- vectorized trace sampler vs the scalar oracle ----------------------------


@pytest.mark.parametrize("rate", [1, 7, 64, 1000])
def test_sample_array_bit_exact_with_scalar(rate):
    from hermes_tpu.obs.tracing import TraceSampler

    for seed in (0, 1, 12345):
        sm = TraceSampler(rate, seed=seed)
        seqs = np.concatenate([np.arange(512, dtype=np.uint64),
                               np.arange(2**63 - 256, 2**63 + 256,
                                         dtype=np.uint64)])
        vec = sm.sample_array(seqs)
        ref = np.array([sm.sample(int(s)) for s in seqs], np.uint16)
        assert (vec == ref).all()
        if rate == 1:
            assert (vec != 0).all()


# -- per-row conn tagging through submit_batch --------------------------------


def test_submit_batch_vector_conn_groups_refusals_like_pump():
    """An ndarray conn tags per row: refusals come back {conn:
    RspBatch} and resolutions emit per packed conn — row-for-row the
    same statuses the scalar-conn path produces."""
    store = KVS(_cfg())
    clock = VirtualClock()
    fe = ColumnarFrontend(store, _scfg(), clock=clock)
    u = fe.u
    k = 12
    rng = np.random.default_rng(3)
    b = wire.ReqBatch(
        kind=np.where(rng.random(k) < 0.5, wire.K_GET,
                      wire.K_PUT).astype(np.uint8),
        req_id=np.arange(1, k + 1, dtype=np.uint32),
        tenant=np.zeros(k, np.uint16), trace=np.zeros(k, np.uint16),
        deadline_us=np.zeros(k, np.uint32),
        key=rng.integers(0, 64, k).astype(np.int64),
        value=rng.integers(0, 99, (k, u)).astype(np.int32))
    # make rows 0 and 5 invalid so the refusal path has something
    bad = b.key.copy()
    bad[0] = -1
    bad[5] = 1 << 40
    b.key = bad
    conn = np.array([pack_conn(i % 2, 1 + i % 3) for i in range(k)],
                    np.int32)
    refusals = fe.submit_batch(b, conn=conn)
    assert isinstance(refusals, dict)
    ref_rows = {int(c): rb for c, rb in refusals.items()}
    assert set(ref_rows) == {int(conn[0]), int(conn[5])}
    for c, rb in ref_rows.items():
        assert (rb.status == wire.S_REJECTED).all()
    # admitted rows resolve grouped by their packed conn
    seen = {}
    for _ in range(200):
        out = fe.pump()
        for c, rb in out.items():
            seen.setdefault(c, 0)
            seen[c] += len(rb)
        if fe.idle():
            break
        clock.advance(1e-3)
    assert fe.idle()
    expected = {}
    for i in range(k):
        if i in (0, 5):
            continue
        expected[int(conn[i])] = expected.get(int(conn[i]), 0) + 1
    assert seen == expected
    verify_columnar(fe)
    for c in seen:
        assert 0 <= conn_worker(c) < 2 and 1 <= conn_local(c) <= 3
        assert pack_conn(conn_worker(c), conn_local(c)) == c


# -- the deterministic shm soak -----------------------------------------------


def test_run_shm_soak_byte_identical_replay():
    kw = dict(cfg=_cfg(n_keys=128, n_sessions=8), scfg=_scfg(),
              n_workers=2, ops_per_worker=192, batch=48, seed=14)
    r1 = run_shm_soak(**kw)
    r2 = run_shm_soak(**kw)
    assert r1["ok"] and r1["checker_ok"]
    assert r1["worker_log_sha"] == r2["worker_log_sha"]
    assert r1["ipc"] == r2["ipc"]
    assert r1["verify"] == r2["verify"]
    assert r1["response_rows"] == [192, 192]
    assert r1["ipc"]["rows_in"] == r1["ipc"]["rows_out"] == 384
    # a different seed is a different byte stream (the digest is not a
    # constant)
    r3 = run_shm_soak(**{**kw, "seed": 15})
    assert r3["worker_log_sha"] != r1["worker_log_sha"]


def test_run_shm_soak_backpressure_shape_is_deterministic():
    """Tiny rings force ring-full skips; determinism must survive the
    backpressure path too."""
    kw = dict(cfg=_cfg(n_keys=128, n_sessions=8), scfg=_scfg(),
              n_workers=3, ops_per_worker=128, batch=32, seed=5,
              nslots=2, slot_rows=16)
    r1 = run_shm_soak(**kw)
    r2 = run_shm_soak(**kw)
    assert r1["worker_log_sha"] == r2["worker_log_sha"]
    assert r1["response_rows"] == [128, 128, 128]


def test_store_owner_rejects_heap_stores():
    store = KVS(_cfg(max_value_bytes=32))
    fe = ColumnarFrontend(store, _scfg())
    rings = [create_ring_pair(fe.u, 2, 8, 0)]
    try:
        with pytest.raises(ValueError, match="fixed-value"):
            StoreOwner(fe, rings)
    finally:
        for a, b in rings:
            a.close()
            b.close()


# -- the real multi-process topology ------------------------------------------


def _batch(cl, u, n_keys, rng, tenant, k=64):
    kind = np.where(rng.random(k) < 0.5, wire.K_GET,
                    wire.K_PUT).astype(np.uint8)
    return wire.ReqBatch(
        kind=kind, req_id=cl.next_ids(k),
        tenant=np.full(k, tenant, np.uint16),
        trace=np.zeros(k, np.uint16),
        deadline_us=np.zeros(k, np.uint32),
        key=rng.integers(0, n_keys, k).astype(np.int64),
        value=rng.integers(0, 99, (k, u)).astype(np.int32))


@pytest.mark.slow
def test_one_store_server_round_trip():
    """2 shm worker processes feeding ONE store: every batched request
    answered, conservation exact, rings cleaned up."""
    from hermes_tpu.serving.rpc import ColumnarClient

    cfg = HermesConfig(n_replicas=4, n_keys=1 << 10, n_sessions=64,
                       value_words=6)
    store = KVS(cfg)
    srv = OneStoreServer(store, _scfg(), n_workers=2, nslots=8,
                         slot_rows=128)
    rng = np.random.default_rng(7)
    try:
        assert srv.alive() == 2
        clients = [ColumnarClient(srv.addr, srv.fe.u) for _ in range(4)]
        for ci, cl in enumerate(clients):
            out = cl.call_batch(_batch(cl, srv.fe.u, cfg.n_keys, rng, ci))
            assert len(out) == 64
            assert all(r.status in (wire.S_OK, wire.S_RETRY_AFTER)
                       for r in out.values())
        for cl in clients:
            cl.close()
    finally:
        srv.close()
    assert srv.pump_error is None
    assert srv.fe.requests == srv.fe.responses
    assert srv.owner.counters()["dead_workers"] == []


@pytest.mark.slow
def test_one_store_survives_worker_kill():
    """kill -9 one worker mid-run: the store and the other worker keep
    serving, the dead worker's clients see EOF (loud, never a hang),
    and frontend conservation still holds."""
    from hermes_tpu.serving.rpc import ColumnarClient

    cfg = HermesConfig(n_replicas=4, n_keys=1 << 10, n_sessions=64,
                       value_words=6)
    store = KVS(cfg)
    srv = OneStoreServer(store, _scfg(), n_workers=2, nslots=8,
                         slot_rows=128)
    rng = np.random.default_rng(11)
    try:
        clients = [ColumnarClient(srv.addr, srv.fe.u) for _ in range(6)]
        for ci, cl in enumerate(clients):
            assert len(cl.call_batch(
                _batch(cl, srv.fe.u, cfg.n_keys, rng, ci))) == 64
        os.kill(srv.procs[0].pid, signal.SIGKILL)
        srv.procs[0].join(5)
        assert srv.alive() == 1
        time.sleep(0.5)
        survived = eof = 0
        for ci, cl in enumerate(clients):
            try:
                out = cl.call_batch(
                    _batch(cl, srv.fe.u, cfg.n_keys, rng, ci))
                assert len(out) == 64
                survived += 1
            except (ConnectionError, OSError):
                eof += 1
        # the kernel had balanced the 6 conns across both workers:
        # the dead worker's conns EOF, the rest keep answering
        assert survived >= 1 and survived + eof == 6
        assert srv.pump_error is None
        for cl in clients:
            cl.close()
    finally:
        srv.close()
    assert srv.owner.dead[0] and not srv.owner.dead[1]
    assert srv.fe.requests == srv.fe.responses
