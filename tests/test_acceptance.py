"""The five BASELINE acceptance scenarios (BASELINE.json:7-11), CI-scaled.

Each scenario runs end-to-end on the fast runtime with history recording and
must drain and pass the linearizability gate; scenario 4 additionally proves
the lease-based membership service detects the injected stall by itself.
"""

import pytest

from hermes_tpu import acceptance


@pytest.mark.parametrize("n", [1, 2, "2r", 3, "3c", 4, 5])
def test_acceptance_config(n):
    counters, verdict = acceptance.run_config(n, scale=0.004, max_steps=4000)
    assert counters["drained"], counters
    assert verdict.ok, (verdict.failures[:2], verdict.undecided[:2])
    assert counters["n_write"] + counters["n_rmw"] > 0
    if n in (2, "2r"):
        assert counters["n_rmw"] > 0


@pytest.mark.parametrize("mix", ["b", "c"])
def test_ycsb_read_heavy_mixes(mix):
    """YCSB-B (95/5) and YCSB-C (read-only) round out the reference's
    workload matrix (SURVEY.md §1 L6); local reads never cross the network,
    so read-heavy mixes mostly exercise the coordinate fast path."""
    from hermes_tpu.config import HermesConfig, WorkloadConfig
    from hermes_tpu.runtime import FastRuntime

    rf = {"b": 0.95, "c": 1.0}[mix]
    cfg = HermesConfig(n_replicas=3, n_keys=256, n_sessions=16, replay_slots=4,
                       ops_per_session=24,
                       workload=WorkloadConfig(read_frac=rf, seed=70 + ord(mix)))
    rt = FastRuntime(cfg, record="array")
    assert rt.drain(400)
    v = rt.check()
    assert v.ok
    c = rt.counters()
    assert c["n_read"] + c["n_write"] + c["n_rmw"] + c["n_abort"] == 3 * 16 * 24
    if mix == "c":
        assert c["n_write"] == 0


def test_acceptance_sparse_variant():
    """Sparse-key client-KVS variant of config 1 (round-2 verdict item 5):
    bulk-preloaded 64-bit keys, 50/50 client mix, checked clean."""
    counters, verdict = acceptance.run_sparse_variant(scale=0.004)
    assert counters["drained"], counters
    assert counters["completed"] == counters["client_ops"]
    assert verdict.ok, (verdict.failures[:2], verdict.undecided[:2])
    assert counters["preload_keys"] >= 64
