"""The five BASELINE acceptance scenarios (BASELINE.json:7-11), CI-scaled.

Each scenario runs end-to-end on the fast runtime with history recording and
must drain and pass the linearizability gate; scenario 4 additionally proves
the lease-based membership service detects the injected stall by itself.
"""

import pytest

from hermes_tpu import acceptance


@pytest.mark.parametrize("n", [1, 2, 3, 4, 5])
def test_acceptance_config(n):
    counters, verdict = acceptance.run_config(n, scale=0.004, max_steps=4000)
    assert counters["drained"], counters
    assert verdict.ok, (verdict.failures[:2], verdict.undecided[:2])
    assert counters["n_write"] + counters["n_rmw"] > 0
    if n == 2:
        assert counters["n_rmw"] > 0
