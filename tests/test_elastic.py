"""Elastic operations (round-10, hermes_tpu/elastic): live resize,
key-range migration routing/rejection/salvage, range-scoped snapshots,
and the rolling-restart drill — every path checker-gated."""

import numpy as np
import pytest

from hermes_tpu import elastic
from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.keyindex import RangeRouter
from hermes_tpu.kvs import KVS, C_REJECTED, StuckOpError
from hermes_tpu.runtime import FastRuntime


def _cfg(**over):
    kw = dict(n_replicas=4, n_keys=64, n_sessions=4, value_words=6,
              replay_slots=8, workload=WorkloadConfig(seed=3))
    kw.update(over)
    return HermesConfig(**kw)


# -- routing ----------------------------------------------------------------


def test_range_router_boundaries_exact():
    """Post-flip routing is EXACT at range boundaries: lo moves, lo-1
    stays; hi-1 moves, hi stays (the satellite's off-by-one probe)."""
    router = RangeRouter(64, default_group=0)
    lo, hi = 16, 32
    router.begin_drain(lo, hi)
    assert bool(router.draining(lo)) and bool(router.draining(hi - 1))
    assert not router.draining(lo - 1) and not router.draining(hi)
    assert int(router.owner(lo)) == 0  # drain does NOT move ownership
    router.flip(lo, hi, 7)
    assert int(router.owner(lo)) == 7 and int(router.owner(hi - 1)) == 7
    assert int(router.owner(lo - 1)) == 0 and int(router.owner(hi)) == 0
    # the flip is atomic: drain cleared in the same update
    assert not router.draining(np.arange(64)).any()
    np.testing.assert_array_equal(
        router.routable(np.array([lo - 1, lo, hi - 1, hi]), 7),
        [False, True, True, False])


def test_range_router_release_and_validation():
    router = RangeRouter(16)
    router.begin_drain(4, 8)
    router.release(4, 8)
    assert not router.draining(np.arange(16)).any()
    assert (router.owner(np.arange(16)) == 0).all()
    with pytest.raises(ValueError):
        router.begin_drain(8, 4)
    with pytest.raises(ValueError):
        router.flip(0, 17, 1)


# -- live resize ------------------------------------------------------------


def test_resize_shrink_grow_under_traffic_checked():
    """Shrink rejects the retired replica's traffic loudly, drains its
    in-flight ops to normal completion, grow re-admits via join value
    sync — checker green with client sessions issuing throughout."""
    kvs = KVS(_cfg(), record=True)
    futs = [kvs.put(r, s, (r * 4 + s) % 64, [r, s])
            for r in range(4) for s in range(4)]
    assert kvs.run_until(futs)
    # queued op on the retiring replica is rejected by the shrink sweep
    queued = kvs.put(3, 0, 7, [1])
    kvs.shrink(3)
    assert queued.done() and queued.result().kind == "rejected"
    # new traffic to the retired replica rejects immediately
    f = kvs.put(3, 1, 5, [9])
    assert f.done() and f.result().kind == "rejected"
    # the shrunken group keeps serving
    f2 = kvs.put(0, 0, 5, [9])
    assert kvs.run_until([f2]) and f2.result().kind == "put"
    kvs.grow(3)
    g = kvs.get(3, 0, 5)
    assert kvs.run_until([g]) and g.result().value[:1] == [9]
    assert kvs.rt.check().ok
    assert kvs.rejected_ops == 2


def test_resize_guards():
    kvs = KVS(_cfg())
    with pytest.raises(ValueError):
        kvs.rt.grow(2)  # already live
    kvs.shrink(2)
    with pytest.raises(ValueError):
        kvs.rt.shrink(2)  # not live anymore
    kvs.grow(2)
    f = kvs.put(2, 0, 1, [1])
    assert kvs.run_until([f]) and f.result().kind == "put"


def test_kvs_shrink_of_non_live_replica_leaves_no_retirement():
    """kvs.shrink validates liveness BEFORE mutating client state: a
    replica removed by other means (detector, crash) must not end up
    silently retired at the KVS when the shrink call is refused."""
    kvs = KVS(_cfg())
    kvs.rt.remove(2)  # detector-style removal, KVS knows nothing
    with pytest.raises(ValueError, match="not live"):
        kvs.shrink(2)
    assert 2 not in kvs._retired
    kvs.rt.join(2, from_replica=0)
    f = kvs.put(2, 0, 1, [1])  # traffic at the rejoined replica serves
    assert kvs.run_until([f]) and f.result().kind == "put"


def test_shrink_refuses_wedged_drain():
    """A replica whose in-flight op cannot drain (quorum frozen) raises
    instead of silently wedging — and rolls the retirement back."""
    kvs = KVS(_cfg())
    kvs.freeze(2)
    kvs.put(1, 0, 5, [1])
    for _ in range(3):
        kvs.step()
    with pytest.raises(RuntimeError, match="did not drain"):
        kvs.shrink(1, drain_steps=5)
    assert 1 not in kvs._retired


def test_shrink_logs_administrative_remove():
    """An elastic shrink lands on the membership log as kind='shrink'
    (administrative), not a detector 'remove'."""
    from hermes_tpu.membership import MembershipService

    cfg = _cfg()
    rt = FastRuntime(cfg)
    svc = MembershipService(cfg, confirm_steps=3)
    rt.attach_membership(svc)
    rt.run(2)
    rt.shrink(1)
    kinds = [e.kind for e in svc.events]
    assert kinds == ["shrink"]
    rt.grow(1)
    assert [e.kind for e in svc.events] == ["shrink", "join"]


# -- key-range migration ----------------------------------------------------


def test_migration_dense_end_to_end_checked():
    cfg = _cfg()
    src, dst = KVS(cfg, record=True), KVS(cfg, record=True)
    router = RangeRouter(cfg.n_keys)
    futs = [src.put(0, 0, k, [k, 100 + k]) for k in range(8, 16)]
    assert src.run_until(futs)
    res = elastic.migrate_range(src, dst, 8, 16, router=router, dst_group=1)
    assert res["drained"] and res["salvaged"] == 0
    assert (router.owner(np.arange(8, 16)) == 1).all()
    assert int(router.owner(7)) == 0 and int(router.owner(16)) == 0
    # src rejects the moved range forever; dst serves it
    f = src.get(0, 0, 9)
    assert f.done() and f.result().kind == "rejected"
    g = dst.get(1, 0, 9)
    assert dst.run_until([g]) and g.result().value[:2] == [9, 109]
    # writes continue the version chain on the destination
    w = dst.put(2, 1, 9, [77])
    assert dst.run_until([w])
    g2 = dst.get(0, 2, 9)
    assert dst.run_until([g2]) and g2.result().value[:1] == [77]
    assert src.rt.check().ok and dst.rt.check().ok


def test_migration_sparse_remaps_client_keys():
    """Sparse mode: migrated client keys re-resolve through the
    destination's KeyIndex (fresh dense slots), values intact, both
    histories checker-green."""
    cfg = _cfg()
    src = KVS(cfg, record=True, sparse_keys=True)
    dst = KVS(cfg, record=True, sparse_keys=True)
    keys = [(i + 1) * 10**12 for i in range(12)]
    futs = [src.put(i % 4, i % 4, k, [i]) for i, k in enumerate(keys)]
    assert src.run_until(futs)
    res = elastic.migrate_range(src, dst, 4, 10)
    assert res["rows"] == 6
    for i in range(4, 10):
        g = dst.get(0, 0, keys[i])
        assert dst.run_until([g])
        assert g.result().found and g.result().value[:1] == [i]
    # boundary slots 3 and 10 stayed on the source
    for i in (3, 10):
        g = src.get(0, 0, keys[i])
        assert src.run_until([g]) and g.result().value[:1] == [i]
    r = src.get(0, 0, keys[4])
    assert r.done() and r.result().kind == "rejected"
    assert src.rt.check().ok and dst.rt.check().ok


def test_migration_mid_drain_ops_rejected_never_dropped():
    """Ops issued to a range mid-drain land as rejected (per-op AND batch
    paths) — counted, resolved, never stranded."""
    cfg = _cfg()
    src = KVS(cfg, record=True)
    futs = [src.put(0, 0, k, [k]) for k in range(8, 16)]
    assert src.run_until(futs)
    src.fence_slots(8, 16)
    f = src.put(1, 1, 9, [5])
    assert f.done() and f.result().kind == "rejected"
    bf = src.submit_batch(
        np.array([KVS.PUT, KVS.PUT], np.int32), np.array([9, 20]),
        np.array([[1], [2]], np.int32))
    assert bf.code[0] == C_REJECTED and not bf.found[0]
    assert src.run_batch(bf)
    assert bf.completion(0).kind == "rejected"
    assert bf.completion(1).kind == "put"
    src.release_slots(8, 16)
    f2 = src.put(1, 1, 9, [5])
    assert src.run_until([f2]) and f2.result().kind == "put"
    assert src.rt.check().ok


def test_migration_salvages_wedged_ops_as_maybe_w():
    """Forced cutover: an op wedged by a frozen quorum member is salvaged
    — future resolves kind='lost', the history holds a maybe_w, BOTH
    checkers stay green, and the destination serves the range."""
    cfg = _cfg()
    src, dst = KVS(cfg, record=True), KVS(cfg, record=True)
    ws = [src.put(0, 0, k, [k]) for k in range(8)]
    assert src.run_until(ws)
    src.freeze(2)
    wedge = src.put(1, 1, 10, [999])
    for _ in range(4):
        src.step()
    assert not wedge.done()
    res = elastic.migrate_range(src, dst, 8, 12, drain_steps=6, force=True)
    assert res["salvaged"] == 1 and not res["drained"]
    assert wedge.done() and wedge.result().kind == "lost"
    src.rt.thaw(2)
    assert src.rt.check().ok
    g = dst.get(0, 0, 10)
    assert dst.run_until([g]) and g.result().found
    assert dst.rt.check().ok


def test_salvage_does_not_strand_queued_ops_behind_salvaged_slot():
    """An op queued BEHIND a salvaged in-flight op (on a key OUTSIDE the
    range) must re-inject after the cutover frees the slot — the salvage
    re-readies freed slots exactly like a crash does."""
    cfg = _cfg()
    src, dst = KVS(cfg, record=True), KVS(cfg, record=True)
    ws = [src.put(0, 0, k, [k]) for k in range(8)]
    assert src.run_until(ws)
    src.freeze(2)
    wedge = src.put(1, 1, 10, [999])   # in the migrating range, will wedge
    queued = src.put(1, 1, 50, [7])    # behind it, key OUTSIDE the range
    for _ in range(3):
        src.step()
    elastic.migrate_range(src, dst, 8, 12, drain_steps=5, force=True)
    assert wedge.done() and wedge.result().kind == "lost"
    src.rt.thaw(2)
    assert src.run_until([queued], max_steps=200)
    assert queued.result().kind == "put"
    assert src.rt.check().ok


def test_migration_cleans_transfer_tempdir(tmp_path, monkeypatch):
    """The default (tempdir) transfer archive is removed on success AND on
    a post-fence failure — range data must not accumulate under /tmp."""
    import tempfile as tempfile_mod

    monkeypatch.setattr(tempfile_mod, "tempdir", str(tmp_path))
    cfg = _cfg()
    src, dst = KVS(cfg, record=True), KVS(cfg, record=True)
    ws = [src.put(0, 0, k, [k]) for k in range(8, 16)]
    assert src.run_until(ws)
    elastic.migrate_range(src, dst, 8, 12)
    assert list(tmp_path.glob("hermes_migrate_*")) == []
    # failure path: wedged drain without force aborts after nothing was
    # archived; wedged drain WITH force archives then completes — cover
    # the abort-after-fence case via a destination that rejects at restore
    src2, dst2 = KVS(cfg, record=True), KVS(cfg, record=True)
    ws = [src2.put(0, 0, k, [k]) for k in range(8, 16)]
    assert src2.run_until(ws)
    import hermes_tpu.snapshot as snap

    real = snap.read_range
    monkeypatch.setattr(snap, "read_range", lambda *a, **k: (_ for _ in ()).throw(
        ValueError("boom")))
    with pytest.raises(ValueError, match="boom"):
        elastic.migrate_range(src2, dst2, 12, 16)
    monkeypatch.setattr(snap, "read_range", real)
    assert list(tmp_path.glob("hermes_migrate_*")) == []
    assert not src2._fence_mask.any()  # abort released the fence


def test_sparse_migration_capacity_checked_before_fence():
    """Sparse mode refuses a migration the destination index cannot hold
    BEFORE fencing (zero side effects) — not at transfer time."""
    cfg = _cfg()
    small = HermesConfig(n_replicas=4, n_keys=4, n_sessions=4,
                         value_words=6, replay_slots=8,
                         workload=WorkloadConfig(seed=3))
    src = KVS(cfg, record=True, sparse_keys=True)
    dst = KVS(small, record=True, sparse_keys=True)
    keys = [(i + 1) * 10**12 for i in range(8)]
    futs = [src.put(0, 0, k, [i]) for i, k in enumerate(keys)]
    assert src.run_until(futs)
    with pytest.raises(ValueError, match="fresh destination slot"):
        elastic.migrate_range(src, dst, 0, 8)
    assert not src._fence_mask.any() and src.rejected_ops == 0


def test_migration_abort_releases_fence_and_drain():
    """A migration that fails mid-drain takes the ABORT path: the fence
    and router drain release, and the source serves the range again —
    never a permanently-unavailable range."""
    cfg = _cfg()
    src, dst = KVS(cfg, record=True), KVS(cfg, record=True)
    router = RangeRouter(cfg.n_keys)
    ws = [src.put(0, 0, k, [k]) for k in range(8)]
    assert src.run_until(ws)
    src.freeze(2)
    src.put(1, 1, 10, [5])
    for _ in range(3):
        src.step()
    with pytest.raises(RuntimeError, match="did not drain"):
        elastic.migrate_range(src, dst, 8, 12, router=router, drain_steps=5)
    assert src.drill_phase is None
    assert not src._fence_mask.any()
    assert not router.draining(np.arange(cfg.n_keys)).any()
    assert (router.owner(np.arange(8, 12)) == 0).all()
    src.rt.thaw(2)
    f = src.put(1, 2, 10, [6])  # the source serves the range again
    assert src.run_until([f]) and f.result().kind == "put"
    assert src.rt.check().ok


def test_migration_destination_must_be_fresh_before_fencing():
    """A refusable migration is refused BEFORE the fence: zero side
    effects on the source (no fence, no rejected ops, no salvage)."""
    cfg = _cfg()
    src, dst = KVS(cfg, record=True), KVS(cfg, record=True)
    fs = [src.put(0, 0, 9, [1]), dst.put(0, 0, 9, [2])]
    assert src.run_until([fs[0]]) and dst.run_until([fs[1]])
    with pytest.raises(ValueError, match="not fresh"):
        elastic.migrate_range(src, dst, 8, 12)
    assert not src._fence_mask.any() and src.rejected_ops == 0
    # dense capacity is also checked up front
    small = KVS(HermesConfig(n_replicas=4, n_keys=8, n_sessions=4,
                             value_words=6, replay_slots=8))
    with pytest.raises(ValueError, match="n_keys"):
        elastic.migrate_range(src, small, 8, 12)
    assert not src._fence_mask.any()


def test_sparse_fence_past_allocation_frontier_rejected():
    """Sparse mode refuses to fence unallocated slots: a fresh client key
    would otherwise allocate INSIDE the draining range."""
    cfg = _cfg()
    kvs = KVS(cfg, sparse_keys=True)
    f = kvs.put(0, 0, 10**15, [1])
    assert kvs.run_until([f])
    with pytest.raises(ValueError, match="frontier"):
        kvs.fence_slots(0, 8)
    kvs.fence_slots(0, 1)  # the allocated prefix is fine


# -- stuck-op drill attribution ---------------------------------------------


def test_stuck_op_diagnostics_carry_drill_phase():
    cfg = _cfg(op_timeout_rounds=3)
    kvs = KVS(cfg, strict_timeouts=True)
    kvs.freeze(2)
    kvs.put(0, 0, 5, [1])
    kvs.drill_phase = "drain"
    with pytest.raises(StuckOpError, match="drill=drain"):
        for _ in range(8):
            kvs.step()
    assert kvs.stuck_ops[0]["drill"] == "drain"
    # no drill active -> no drill field
    kvs2 = KVS(cfg, strict_timeouts=False)
    kvs2.freeze(2)
    kvs2.put(0, 0, 5, [1])
    for _ in range(8):
        kvs2.step()
    assert kvs2.stuck_ops and "drill" not in kvs2.stuck_ops[0]


# -- rolling-restart drill --------------------------------------------------


def _drill_cfg():
    return HermesConfig(
        n_replicas=4, n_keys=96, n_sessions=4, replay_slots=6,
        ops_per_session=48, replay_age=6, replay_scan_every=4,
        rebroadcast_every=2, lease_steps=6, pipeline_depth=2,
        workload=WorkloadConfig(read_frac=0.4, rmw_frac=0.25, seed=7))


def test_rolling_restart_drill_all_replicas_checked():
    rt = FastRuntime(_drill_cfg(), record=True)
    res = elastic.run_rolling_restart(rt, start=4, spacing=8, check=True)
    assert res["restarts"] == 4
    assert res["drained"] and res["checked_ok"]
    dip = res["dip"]
    assert dip["dip_pct"] is not None and dip["windows"] > 0
    assert "worst_window" in dip


def test_rolling_restart_schedule_deterministic():
    """Same seed + config => byte-identical executed log and final state
    (the drill rides the chaos subsystem's determinism contract)."""
    import jax
    from hermes_tpu import chaos

    logs, states = [], []
    for _ in range(2):
        cfg = _drill_cfg()
        rt = FastRuntime(cfg, record=True)
        sched = chaos.Schedule.rolling_restart(cfg, start=4, spacing=8)
        runner = chaos.ChaosRunner(
            rt, sched, spec=chaos.ChaosSpec(min_healthy=2))
        res = runner.run(44, check=True)
        assert res["checked_ok"]
        logs.append(runner.log_json())
        states.append(jax.tree.leaves(jax.device_get(rt.fs)))
    assert logs[0] == logs[1]
    for x, y in zip(states[0], states[1]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_rolling_resize_drill_checked():
    kvs = KVS(_cfg(ops_per_session=1), record=True)
    bf = elastic.submit_drill_mix(kvs, 600, seed=5)
    res = elastic.rolling_resize(kvs, hold_steps=4, check=True)
    assert kvs.run_batch(bf)
    assert res["resizes"] == 4 and res["checked_ok"]
    assert res["dip"]["dip_pct"] is not None
