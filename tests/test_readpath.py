"""Round-16 read side: the local-read fast path (core/readpath.py),
KVS.multi_get/scan with read-your-writes fencing, the stale-read checker
extension (red-tested on both engines), the fleet fan/merge, the serving
K_MGET/K_SCAN verbs, and the read-path op budget."""

import dataclasses

import numpy as np
import pytest

from hermes_tpu.checker import linearizability as lin
from hermes_tpu.config import FleetConfig, HermesConfig, WorkloadConfig
from hermes_tpu.core import types as t
from hermes_tpu.kvs import C_REJECTED, KVS


def _cfg(**over):
    kw = dict(n_replicas=3, n_keys=256, value_words=6, n_sessions=8,
              replay_slots=8, ops_per_session=64,
              workload=WorkloadConfig(read_frac=0.5, seed=3))
    kw.update(over)
    return HermesConfig(**kw)


def _put_all(kvs, pairs):
    futs = [kvs.put(i % kvs.cfg.n_replicas, i % kvs.cfg.n_sessions, k, v)
            for i, (k, v) in enumerate(pairs)]
    assert kvs.run_until(futs)
    return futs


# -- KVS fast path -----------------------------------------------------------


def test_multi_get_serves_locally_and_checks():
    kvs = KVS(_cfg(), record=True)
    _put_all(kvs, [(7, [11, 22, 33]), (9, [44, 55, 66])])
    res = kvs.multi_get([7, 9, 3])
    assert res.all_done() and res.local.all()
    assert res.value[0].tolist()[:3] == [11, 22, 33]
    assert res.value[1].tolist()[:3] == [44, 55, 66]
    # slot 3 never written: the preloaded initial value (uid (3, -1))
    assert res.found[2]
    assert kvs.read_stats()["local_reads"] == 3
    v = kvs.rt.check()
    assert v.ok
    assert lin.stale_read(kvs.rt.history_ops()) == []


def test_scan_dense_range_and_bounds():
    kvs = KVS(_cfg(), record=True)
    _put_all(kvs, [(10, [5, 5]), (12, [6, 6])])
    res = kvs.scan(9, 13)
    assert res.all_done()
    assert res.key.tolist() == [9, 10, 11, 12]
    assert res.value[1].tolist()[:2] == [5, 5]
    assert res.value[3].tolist()[:2] == [6, 6]
    with pytest.raises(ValueError):
        kvs.scan(5, 3)
    with pytest.raises(ValueError):
        kvs.scan(0, kvs.cfg.n_keys + 1)
    assert kvs.rt.check().ok


def test_multi_get_sparse_absent_not_found_no_slot():
    kvs = KVS(_cfg(), sparse_keys=True)
    big = 0xDEAD_BEEF_0000_0001
    f = kvs.put(0, 0, big, [9, 9, 9])
    assert kvs.run_until([f])
    used = kvs.index.n_used
    res = kvs.multi_get([big, 0xFFFF_0000])
    assert res.all_done()
    assert res.found[0] and res.value[0].tolist()[:3] == [9, 9, 9]
    assert not res.found[1]
    # the absent probe claimed no dense slot
    assert kvs.index.n_used == used


def test_scan_sparse_echoes_client_keys_in_write_order():
    kvs = KVS(_cfg(), sparse_keys=True)
    keys = [1 << 40, 77, 1 << 50]
    _put_all(kvs, [(k, [i + 1]) for i, k in enumerate(keys)])
    res = kvs.scan(0, kvs.cfg.n_keys)
    assert res.all_done()
    assert res.key.tolist() == keys  # slots allocate in first-write order
    assert [r[0] for r in res.value.tolist()] == [1, 2, 3]


def test_invalid_key_falls_back_to_round_path():
    """A key whose write is still in flight is NOT Valid: the fast path
    must decline it (no stale bytes) and the round-path fallback must
    resolve once the write commits."""
    kvs = KVS(_cfg(), record=True)
    kvs.freeze(2)  # quorum needs every live replica: the put stalls
    fw = kvs.put(0, 0, 5, [1, 2, 3])
    for _ in range(4):
        kvs.step()
    assert not fw.done()
    res = kvs.multi_get([5, 6], wait=False)
    assert not res.local[0]          # in-flight key declined
    assert res.local[1]              # untouched key served locally
    assert not res.all_done()
    assert kvs.read_stats()["fallback_reads"] == 1
    kvs.rt.thaw(2)
    assert kvs.run_until([fw])
    assert kvs.run_batch(res._fallback[0])
    res._pull()
    assert res.all_done()
    assert res.value[0].tolist()[:3] == [1, 2, 3]
    assert kvs.rt.check().ok
    assert lin.stale_read(kvs.rt.history_ops()) == []


def test_no_healthy_replica_means_no_local_serving():
    kvs = KVS(_cfg())
    for r in range(3):
        kvs.freeze(r)
    res = kvs.multi_get([1, 2], wait=False)
    assert not res.local.any()
    assert res.fallbacks == 2  # everything routed to the round path


def test_ryw_fence_redirects_to_round_path():
    """Red-style fence check: a poisoned fence entry (a committed ts the
    row can never have reached) must force the lane's local read onto
    the round path — and the answer is still the committed value."""
    kvs = KVS(_cfg())
    f = kvs.put(0, 0, 42, [7, 8, 9])
    assert kvs.run_until([f])
    # fence satisfied: a normal session read serves locally and prunes
    res = kvs.multi_get([42], session=(0, 0))
    assert res.local[0] and kvs.ryw_fallbacks == 0
    # poison: pretend the lane saw a commit far in the version future
    kvs._ryw[(0, 0)] = {42: (1 << 40, 0)}
    res2 = kvs.multi_get([42], session=(0, 0))
    assert res2.all_done()
    assert not res2.local[0]
    assert kvs.ryw_fallbacks == 1
    assert res2.value[0].tolist()[:3] == [7, 8, 9]
    # unfenced sessions are unaffected
    res3 = kvs.multi_get([42], session=(1, 0))
    assert res3.local[0]


def test_fenced_range_rejects_reads():
    kvs = KVS(_cfg())
    kvs.fence_slots(10, 20)
    res = kvs.multi_get([5, 15])
    assert res.code[0] == t.C_READ and res.code[1] == C_REJECTED
    sc = kvs.scan(8, 12)
    assert (sc.code[:2] == t.C_READ).all()
    assert (sc.code[2:] == C_REJECTED).all()


def test_ryw_holds_under_seeded_chaos_depth2():
    """Acceptance: read-your-writes under a seeded chaos schedule at
    pipeline depth 2 — every committed put is immediately observable by
    the same lane's multi_get, through freeze/thaw windows, and the
    whole run stays checker-green with stale_read == []."""
    from hermes_tpu import chaos as chaos_lib

    cfg = _cfg(pipeline_depth=2, n_keys=64)
    kvs = KVS(cfg, record=True)
    rng = np.random.default_rng(14)
    lines = []
    step = 0
    for _ in range(4):
        r = int(rng.integers(0, cfg.n_replicas))
        fr, th = step + int(rng.integers(1, 4)), step + int(rng.integers(5, 9))
        lines += [f"@{fr} freeze {r}", f"@{th} thaw {r}"]
        step = th + 2
    sched = chaos_lib.Schedule.parse("\n".join(lines))
    runner = chaos_lib.ChaosRunner(kvs, sched)
    lane = (0, 1)
    payload = 1
    for i in range(40):
        runner.tick(i)
        if i % 3 == 0:
            key = int(rng.integers(0, cfg.n_keys))
            fut = kvs.put(*lane, key, [payload, i])
            assert kvs.run_until([fut], max_steps=500)
            c = fut.result()
            if c.kind == "put":  # committed and client-visible
                res = kvs.multi_get([key], session=lane)
                assert res.all_done()
                got = res.value[0].tolist()[:2]
                # RYW: the lane observes its own committed write (or a
                # newer one — no other writer touches this payload space)
                assert got == [payload, i], (key, got, payload, i)
            payload += 1
        else:
            kvs.step()
    for r in range(cfg.n_replicas):
        kvs.rt.thaw(r)
    kvs.rt.flush_pipeline()
    kvs.flush()
    assert kvs.rt.check().ok
    assert lin.stale_read(kvs.rt.history_ops()) == []


# -- the stale-read checker (red on both engines) ----------------------------


def _inject_stale_read(kvs, key: int):
    """Deliberately record a read of the key's OVERWRITTEN value at a
    step after the overwrite committed — the exact bug class the checker
    extension exists to catch."""
    from hermes_tpu.core import state as st

    f1 = kvs.put(0, 0, key, [1])
    assert kvs.run_until([f1])
    uid1 = f1.result().uid
    f2 = kvs.put(1, 1, key, [2])
    assert kvs.run_until([f2])
    for _ in range(3):
        kvs.step()
    n = 1
    rval = np.zeros((1, n, kvs.cfg.value_words), np.int32)
    rval[0, 0, 0], rval[0, 0, 1] = uid1
    step = np.full((1, n), kvs.rt.step_idx, np.int32)
    kvs.rt.recorder.record_step(st.Completions(
        code=np.full((1, n), t.C_READ, np.int32),
        key=np.full((1, n), key, np.int32),
        wval=np.zeros((1, n, kvs.cfg.value_words), np.int32),
        rval=rval,
        ver=np.zeros((1, n), np.int32), fc=np.zeros((1, n), np.int32),
        invoke_step=step, commit_step=step,
    ))


@pytest.mark.parametrize("recorder", [True, "array"])
def test_stale_read_red_batched(recorder):
    kvs = KVS(_cfg(), record=recorder)
    _inject_stale_read(kvs, 13)
    ev = lin.stale_read(kvs.rt.history_ops())
    assert ev, "injected stale read not caught on the batched engine"
    assert ev[0]["key"] == 13
    # a clean sibling run stays green
    kvs2 = KVS(_cfg(), record=recorder)
    _put_all(kvs2, [(13, [1]), (13, [2])])
    assert kvs2.multi_get([13]).all_done()
    assert lin.stale_read(kvs2.rt.history_ops()) == []


def test_stale_read_red_sharded(cpu_devices):
    from jax.sharding import Mesh

    mesh = Mesh(np.array(cpu_devices[:3]), ("replica",))
    kvs = KVS(_cfg(), backend="sharded", mesh=mesh, record="array")
    _inject_stale_read(kvs, 21)
    ev = lin.stale_read(kvs.rt.history_ops())
    assert ev, "injected stale read not caught on the sharded engine"
    assert ev[0]["key"] == 21


def test_sharded_multi_get_serves_and_checks(cpu_devices):
    from jax.sharding import Mesh

    mesh = Mesh(np.array(cpu_devices[:3]), ("replica",))
    kvs = KVS(_cfg(), backend="sharded", mesh=mesh, record="array")
    _put_all(kvs, [(3, [30, 31]), (200, [40, 41])])
    res = kvs.multi_get([3, 200])
    assert res.all_done() and res.local.all()
    assert res.value[0].tolist()[:2] == [30, 31]
    assert res.value[1].tolist()[:2] == [40, 41]
    assert kvs.rt.check().ok
    assert lin.stale_read(kvs.rt.history_ops()) == []


# -- fleet -------------------------------------------------------------------


def test_fleet_multi_get_merges_in_fleet_key_order():
    base = _cfg(n_keys=64, n_sessions=4)
    fleet_cfg = FleetConfig(groups=2, base=base)
    from hermes_tpu.fleet import Fleet

    fleet = Fleet(fleet_cfg, record="array")
    keys = np.array([3, 100, 70, 5], np.int64)
    vals = (np.arange(16, dtype=np.int32).reshape(4, 4) + 1)
    fb = fleet.submit_batch(np.full(4, Fleet.PUT, np.int32), keys, vals)
    assert fleet.run_batch(fb)
    fr = fleet.multi_get(keys[::-1], session=9)
    assert fr.all_done() and fr.local.all()
    # answers land at the FLEET submission positions, spanning groups
    assert fr.value[0].tolist() == vals[3].tolist()   # key 5 (group 0)
    assert fr.value[1].tolist() == vals[2].tolist()   # key 70 (group 1)
    assert fr.value[3].tolist() == vals[0].tolist()   # key 3
    assert set(fr.group.tolist()) == {0, 1}
    sc = fleet.scan(60, 68)  # spans the group boundary at 64
    assert sc.all_done() and set(sc.group.tolist()) == {0, 1}
    assert fleet.check()["ok"]


def test_fleet_multi_get_draining_range_rejects():
    base = _cfg(n_keys=64, n_sessions=4)
    from hermes_tpu.fleet import Fleet

    fleet = Fleet(FleetConfig(groups=2, base=base))
    fleet.router.begin_drain(0, 8)
    fr = fleet.multi_get([3, 100])
    assert fr.code[0] == C_REJECTED and fr.group[0] == -1
    assert fr.code[1] == t.C_READ
    fleet.router.release(0, 8)


# -- serving (K_MGET / K_SCAN) -----------------------------------------------


def test_wire_read_structs_roundtrip_and_red():
    from hermes_tpu.serving import wire

    u = 4
    req = wire.ReadRequest(kind="mget", req_id=9, tenant=3,
                           keys=[2, 5, 11], deadline_us=500)
    assert wire.decode_any_request(wire.encode_any_request(req, u), u) == req
    sc = wire.ReadRequest(kind="scan", req_id=10, tenant=0, lo=4, hi=20)
    assert wire.decode_any_request(wire.encode_any_request(sc, u), u) == sc
    rsp = wire.ReadResponse(
        status=wire.S_OK, req_id=9, step=7, found=[True, False, True],
        local=[True, True, False], codes=[0, 0, wire.RK_REJECTED],
        values=[[1, 2, 3, 4], [0] * 4, [5, 6, 7, 8]])
    assert wire.decode_any_response(
        wire.encode_any_response(rsp, u), u) == rsp
    # refusal carries no rows
    ref = wire.ReadResponse(status=wire.S_RETRY_AFTER, req_id=9,
                            reason=wire.R_SHED_READ, retry_after_us=100)
    assert wire.decode_any_response(
        wire.encode_any_response(ref, u), u) == ref
    # red: truncated body / empty mget / bad magic all refuse loudly
    with pytest.raises(ValueError):
        wire.decode_read_request(wire.encode_read_request(req)[:-3])
    with pytest.raises(ValueError):
        wire.encode_read_request(wire.ReadRequest(
            kind="mget", req_id=1, tenant=0, keys=[]))
    with pytest.raises(ValueError):
        wire.decode_read_response(b"\x00" * wire._RRSP.size, u)
    # both request layouts expose req_id to the header peek
    assert wire.peek_req_id(wire.encode_read_request(req)) == 9


def test_serving_mget_scan_end_to_end_loopback():
    from hermes_tpu.serving import (Frontend, LoopbackServer, ServingConfig,
                                    VirtualClock, verify_serving, wire)

    kvs = KVS(_cfg(n_keys=128), record="array")
    clock = VirtualClock()
    fe = Frontend(kvs, ServingConfig(), clock=clock)
    lb = LoopbackServer(fe)
    for i, k in enumerate((4, 8, 15)):
        assert lb.submit(wire.Request(kind="put", req_id=100 + i, tenant=0,
                                      key=k, value=[k, k + 1])) is None
    for _ in range(6):
        lb.pump()
        clock.advance(0.001)
    assert lb.submit(wire.ReadRequest(kind="mget", req_id=200, tenant=1,
                                      keys=[4, 8, 15, 99])) is None
    assert lb.submit(wire.ReadRequest(kind="scan", req_id=201, tenant=1,
                                      lo=6, hi=10)) is None
    rsps = []
    for _ in range(6):
        rsps += lb.pump()
        clock.advance(0.001)
    reads = {r.req_id: r for r in rsps
             if isinstance(r, wire.ReadResponse)}
    assert set(reads) == {200, 201}
    m = reads[200]
    assert m.status == wire.S_OK and all(m.local)
    assert m.values[0][:2] == [4, 5] and m.values[2][:2] == [15, 16]
    s = reads[201]
    assert s.values[2][:2] == [8, 9]
    # malformed: out-of-range key refuses loudly, in the read layout
    bad = lb.submit(wire.ReadRequest(kind="mget", req_id=202, tenant=1,
                                     keys=[5, 10_000]))
    assert isinstance(bad, wire.ReadResponse)
    assert bad.status == wire.S_REJECTED
    lb.drain()
    verify_serving(fe)
    assert kvs.rt.check().ok
    assert lin.stale_read(kvs.rt.history_ops()) == []


def test_serving_mget_over_real_sockets():
    from hermes_tpu.serving import (Frontend, RpcClient, ServingConfig,
                                    TcpRpcServer)

    kvs = KVS(_cfg(n_keys=128))
    fe = Frontend(kvs, ServingConfig(tenant_rate_per_s=1e6,
                                     tenant_burst=1e4))
    srv = TcpRpcServer(fe)
    try:
        cli = RpcClient(srv.addr, fe.u)
        # no deadline: the first pump compiles the round program, which
        # can take seconds on a cold CPU backend
        put = cli.call("put", 33, value=[7, 7])
        assert put.status_name == "ok"
        rsp = cli.call_mget([33, 34])
        assert rsp.status_name == "ok"
        assert rsp.values[0][:2] == [7, 7]
        sc = cli.call_scan(30, 36)
        assert sc.status_name == "ok" and len(sc.values) == 6
        cli.close()
    finally:
        srv.close()
    assert srv.pump_error is None


def test_serving_ryw_fence_is_tenant_scoped():
    """The frontend pins a per-tenant fence token on every commit it
    delivers, so lane rotation on the write path cannot defeat RYW for
    batched reads: after a tenant's put resolves, its K_MGET carries the
    same token; a poisoned fence reroutes the read to the round path
    and the answer is still the committed value."""
    from hermes_tpu.serving import (Frontend, LoopbackServer, ServingConfig,
                                    VirtualClock, wire)

    kvs = KVS(_cfg(n_keys=64))
    clock = VirtualClock()
    fe = Frontend(kvs, ServingConfig(), clock=clock)
    lb = LoopbackServer(fe)
    assert lb.submit(wire.Request(kind="put", req_id=1, tenant=7, key=9,
                                  value=[3, 4])) is None
    rsps = []
    for _ in range(4):
        rsps += lb.pump()
        clock.advance(0.001)
    assert any(r.status == wire.S_OK and r.uid is not None for r in rsps)
    token = ("tenant", 7)
    assert token in kvs._ryw and 9 in kvs._ryw[token]
    # satisfied fence: served locally, entry pruned
    assert lb.submit(wire.ReadRequest(kind="mget", req_id=2, tenant=7,
                                      keys=[9])) is None
    rsps = []
    for _ in range(4):
        rsps += lb.pump()
        clock.advance(0.001)
    m = [r for r in rsps if isinstance(r, wire.ReadResponse)][0]
    assert m.local[0] and m.values[0][:2] == [3, 4]
    assert 9 not in kvs._ryw.get(token, {})
    # poisoned fence: the read reroutes (not local), answer still right
    kvs._ryw[token] = {9: (1 << 40, 0)}
    assert lb.submit(wire.ReadRequest(kind="mget", req_id=3, tenant=7,
                                      keys=[9])) is None
    rsps = []
    for _ in range(6):
        rsps += lb.pump()
        clock.advance(0.001)
    m = [r for r in rsps if isinstance(r, wire.ReadResponse)][0]
    assert not m.local[0] and m.values[0][:2] == [3, 4]
    assert kvs.ryw_fallbacks == 1


def test_batch_writers_can_pin_read_fences():
    """BatchFutures carries the committed timestamps (tsv/tsf), and
    pin_read_fence installs them under an arbitrary token — the batch
    path's route to read-your-writes."""
    kvs = KVS(_cfg())
    bf = kvs.submit_batch(np.array([KVS.PUT], np.int32), np.array([17]),
                          np.array([[5, 6, 7, 8]], np.int32))
    assert kvs.run_batch(bf)
    c = bf.completion(0)
    assert c.ts is not None and c.ts[0] > 0
    kvs.pin_read_fence("my-batch", 17, c.ts)
    res = kvs.multi_get([17], session="my-batch")
    assert res.local[0] and res.value[0].tolist()[:2] == [5, 6]
    assert 17 not in kvs._ryw["my-batch"]  # satisfied -> pruned


def test_scan_probe_cannot_hide_cold_interior_behind_hot_endpoints():
    """Rung 2 must shed a scan whose ENDPOINTS are hot but whose
    interior is cold (the probe hunts len(hot)+1 keys from lo, which
    provably contains a cold one)."""
    from hermes_tpu.serving import (Frontend, LoopbackServer, ServingConfig,
                                    VirtualClock, wire)

    kvs = KVS(_cfg(n_keys=64))
    scfg = ServingConfig(hot_keys=(0, 31), queue_cap=16,
                         shed_write_frac=0.3, shed_read_frac=0.5)
    fe = Frontend(kvs, scfg, clock=VirtualClock())
    lb = LoopbackServer(fe)
    for i in range(10):  # jam past the rung-2 watermark with hot gets
        assert lb.submit(wire.Request(kind="get", req_id=100 + i, tenant=0,
                                      key=(0, 31)[i % 2])) is None
    rsp = lb.submit(wire.ReadRequest(kind="scan", req_id=1, tenant=1,
                                     lo=0, hi=32))
    assert rsp is not None and rsp.reason == wire.R_SHED_READ
    lb.drain()


def test_plausible_frame_length_predicates():
    from hermes_tpu.serving import wire

    u = 4
    req_ok = wire.plausible_request_len(u)
    assert req_ok(wire.req_nbytes(u))
    assert req_ok(wire.rreq_nbytes("mget", 3))
    assert req_ok(wire.rreq_nbytes("scan", 0))
    assert not req_ok(wire.req_nbytes(u) + 1)
    assert not req_ok(wire._RREQ.size + 3)  # not a whole key vector
    rsp_ok = wire.plausible_response_len(u)
    assert rsp_ok(wire.rsp_nbytes(u))
    assert rsp_ok(wire.rrsp_nbytes(u, 0))
    assert rsp_ok(wire.rrsp_nbytes(u, 5))
    assert not rsp_ok(wire.rrsp_nbytes(u, 5) + 2)


# -- op budget ---------------------------------------------------------------


def test_read_programs_hold_their_op_budget():
    """The read path's own census: ONE dynamic gather for a multi-get,
    ZERO sparse ops for a scan — and nothing on the collective chain.
    (The round census being untouched is enforced by the census gate:
    the batched/sharded sections of OP_BUDGET.json did not move.)"""
    from hermes_tpu.core import readpath

    cfg = _cfg(n_keys=1024)
    c = readpath.read_census(cfg, "batched", batch=512)
    assert c["sparse_total"] == 1
    assert c["stablehlo.gather"] == 1
    assert c["collective_total"] == 0
    s = readpath.scan_census(cfg, "batched", size=512)
    assert s["sparse_total"] == 0
    assert s["collective_total"] == 0


def test_batch_bucket_pads_to_fixed_shapes():
    from hermes_tpu.core import readpath

    assert readpath.batch_bucket(1) == readpath.MIN_BATCH
    assert readpath.batch_bucket(257) == 512
    assert readpath.batch_bucket(512) == 512
    kvs = KVS(_cfg())
    kvs.multi_get(list(range(5)))
    kvs.multi_get(list(range(9)))  # same bucket: no new compile
    rd = kvs._reader
    from hermes_tpu.core.readpath import build_multi_get

    assert build_multi_get.cache_info().currsize >= 1


# -- workloads ---------------------------------------------------------------


def test_read_mixes_shapes_and_matrix():
    from hermes_tpu.workload.openloop import make_mix, scenario_matrix
    from hermes_tpu.workload.ycsb import READ_MIXES

    assert READ_MIXES["b"]["read_frac"] == 0.95
    assert READ_MIXES["c"]["read_frac"] == 1.0
    assert READ_MIXES["d"]["distribution"] == "latest"
    names = [m.name for m in scenario_matrix()]
    for want in ("ycsb_b", "ycsb_c", "ycsb_d"):
        assert want in names
    # measured read ratio tracks the spec
    from hermes_tpu.workload.openloop import MixSpec

    spec = MixSpec(name="ycsb_b", **READ_MIXES["b"])
    mix = make_mix(spec, 1024, 4000, seed=5)
    frac = float(np.mean(mix["kind"] == 0))
    assert 0.93 < frac < 0.97


def test_latest_distribution_reads_chase_the_write_frontier():
    from hermes_tpu.workload.openloop import MixSpec, make_mix
    from hermes_tpu.workload.ycsb import READ_MIXES

    spec = MixSpec(name="ycsb_d", **READ_MIXES["d"])
    n_keys = 1 << 16  # huge keyspace: uniform reads would rarely collide
    mix = make_mix(spec, n_keys, 3000, seed=7)
    m2 = make_mix(spec, n_keys, 3000, seed=7)
    assert mix["key"].tobytes() == m2["key"].tobytes()  # deterministic
    written = set()
    hits = reads = 0
    for i in range(3000):
        if mix["kind"][i] != 0:
            written.add(int(mix["key"][i]))
        elif written:
            reads += 1
            hits += int(mix["key"][i]) in written
    # latest reads overwhelmingly land on already-written keys
    assert reads > 1000 and hits / reads > 0.9
