"""Round-20 satellite: scripts/native_sanitize.sh in CI.

The script builds the native C++ components (tcp_transport / checker
core + the standalone harness) under ASan+UBSan and TSan and runs them.
Two tiers:

  * quick — the script and its inputs exist, and the toolchain
    situation is reported LOUDLY: present (the slow tier will build) or
    absent (skip with a message naming what's missing — a silently
    green CI with no compiler is how sanitizer coverage rots).
  * slow (``test_native_sanitizer_suite``) — actually build + run both
    sanitizer variants via the script; any sanitizer report is a
    non-zero exit and fails the test with the full output attached.
"""

import pathlib
import shutil
import subprocess

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
SCRIPT = REPO / "scripts" / "native_sanitize.sh"
NATIVE = REPO / "hermes_tpu" / "native"
SOURCES = ("native_test.cpp", "tcp_transport.cpp", "checker_core.cpp")


def _toolchain_missing():
    """None when buildable, else a LOUD human reason for skipping."""
    if shutil.which("g++") is None:
        return "g++ not on PATH: native sanitizer suite NOT RUN"
    probe = subprocess.run(
        ["g++", "-fsanitize=address", "-x", "c++", "-", "-o",
         "/tmp/hermes_san_probe", "-pthread"],
        input=b"int main(){return 0;}", capture_output=True)
    if probe.returncode != 0:
        return ("g++ present but sanitizer runtimes unavailable "
                "(libasan probe failed): native sanitizer suite NOT "
                "RUN\n" + probe.stderr.decode(errors="replace")[-500:])
    return None


def test_native_sanitize_script_wired():
    """The CI wiring itself: script exists, is executable-shaped, and
    names exactly the sources that exist on disk."""
    assert SCRIPT.exists(), f"{SCRIPT} missing"
    text = SCRIPT.read_text()
    assert text.startswith("#!"), "script lost its shebang"
    assert "set -euo pipefail" in text, (
        "script must fail loudly on any build/run error")
    for src in SOURCES:
        assert src in text, f"script no longer builds {src}"
        assert (NATIVE / src).exists(), f"{src} missing from native/"
    assert "fsanitize=address" in text and "fsanitize=thread" in text
    # the toolchain situation is part of the quick tier's signal: CI
    # logs show WHY the slow tier will build or skip
    missing = _toolchain_missing()
    if missing:
        print(f"NOTE: {missing}")
    else:
        print("NOTE: toolchain present; slow tier will build+run the "
              "sanitizer suite")


def test_native_sanitizer_suite():
    """Slow tier: the actual ASan+UBSan and TSan build-and-run."""
    missing = _toolchain_missing()
    if missing:
        pytest.skip(missing)
    r = subprocess.run(["bash", str(SCRIPT)], capture_output=True,
                       timeout=900)
    out = r.stdout.decode(errors="replace")
    err = r.stderr.decode(errors="replace")
    assert r.returncode == 0, (
        f"native sanitizer suite FAILED (rc={r.returncode}):\n"
        f"--- stdout ---\n{out[-3000:]}\n--- stderr ---\n{err[-3000:]}")
    assert "native sanitizer pass complete" in out
