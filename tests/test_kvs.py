"""Client KVS API (hermes_tpu/kvs.py) — the reference's session-based
get/put/RMW surface (SURVEY.md §1 L5) over the protocol runtime."""

import numpy as np

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.kvs import KVS


def mk(**kw):
    base = dict(n_replicas=3, n_keys=256, n_sessions=8, replay_slots=4,
                value_words=6, replay_age=4, replay_scan_every=4)
    base.update(kw)
    return KVS(HermesConfig(**base), record=True)


def test_put_get_roundtrip_remote_replica():
    kvs = mk()
    fp = kvs.put(0, 0, key=7, value=[11, 22, 33, 44])
    assert kvs.run_until([fp])
    assert fp.result().kind == "put"
    # the write is replicated: replica 2 reads it locally
    fg = kvs.get(2, 0, key=7)
    assert kvs.run_until([fg])
    assert fg.result().value == [11, 22, 33, 44]
    # and the writer reads its own write
    fo = kvs.get(0, 1, key=7)
    assert kvs.run_until([fo])
    assert fo.result().value == [11, 22, 33, 44]


def test_get_untouched_key_returns_initial():
    kvs = mk()
    f = kvs.get(1, 0, key=42)
    assert kvs.run_until([f])
    assert f.result().value == [0, 0, 0, 0]


def test_concurrent_puts_same_key_converge():
    kvs = mk()
    fa = kvs.put(0, 0, key=9, value=[100])
    fb = kvs.put(1, 0, key=9, value=[200])
    assert kvs.run_until([fa, fb])
    # both commit (plain writes never abort); all replicas agree on the winner
    reads = [kvs.get(r, 2, key=9) for r in range(3)]
    assert kvs.run_until(reads)
    vals = [f.result().value for f in reads]
    assert vals[0] == vals[1] == vals[2]
    assert vals[0][0] in (100, 200)


def test_rmw_reads_displaced_value():
    kvs = mk()
    f1 = kvs.put(0, 0, key=5, value=[1])
    assert kvs.run_until([f1])
    f2 = kvs.rmw(1, 0, key=5, value=[2])
    assert kvs.run_until([f2])
    c = f2.result()
    assert c.kind == "rmw"
    assert c.value == [1, 0, 0, 0]
    f3 = kvs.get(2, 0, key=5)
    assert kvs.run_until([f3])
    assert f3.result().value == [2, 0, 0, 0]


def test_session_queueing_fifo():
    kvs = mk()
    futs = [kvs.put(0, 3, key=1, value=[i]) for i in range(5)]
    futs.append(kvs.get(0, 3, key=1))
    assert kvs.run_until(futs)
    assert futs[-1].result().value == [4, 0, 0, 0]


def test_survives_replica_failure():
    kvs = mk(n_replicas=4)
    f1 = kvs.put(0, 0, key=3, value=[7])
    assert kvs.run_until([f1])
    kvs.freeze(3)
    kvs.remove(3)
    f2 = kvs.put(1, 0, key=3, value=[8])
    f3 = kvs.get(0, 1, key=3)
    assert kvs.run_until([f2, f3], max_steps=2000)
    fg = kvs.get(2, 0, key=3)
    assert kvs.run_until([fg])
    assert fg.result().value == [8, 0, 0, 0]


def test_checked_client_run():
    """Client traffic records a history the linearizability gate accepts."""
    kvs = mk()
    rng = np.random.default_rng(0)
    futs = []
    for i in range(60):
        r = int(rng.integers(3))
        s = int(rng.integers(8))
        k = int(rng.integers(16))
        if rng.random() < 0.5:
            futs.append(kvs.get(r, s, k))
        else:
            futs.append(kvs.put(r, s, k, [int(rng.integers(1000))]))
    assert kvs.run_until(futs)
    assert kvs.rt.check().ok


def test_kvs_sharded_backend_roundtrip():
    """The client API over the sharded (tpu_ici-shaped) backend: puts and
    remote gets work across the 8-device mesh exactly as batched."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    cfg = HermesConfig(n_replicas=8, n_keys=128, n_sessions=4, value_words=6)
    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    kvs = KVS(cfg, backend="sharded", mesh=mesh)
    f = kvs.put(0, 0, 17, [123, 456])
    assert kvs.run_until([f], max_steps=200)
    g = kvs.get(7, 1, 17)  # farthest replica reads locally after VAL
    assert kvs.run_until([g], max_steps=200)
    assert g.result().value[:2] == [123, 456]


def test_kvs_client_path_at_scale_checked(monkeypatch):
    """>=10k client ops through the session API complete and check clean
    (round-2 verdict item 7); the vectorized completion matcher keeps
    per-round cost flat in the in-flight count.  (Throughput itself is a
    bench concern — scripts/kvs_scale.py reports it — not asserted here.)"""
    import os
    monkeypatch.syspath_prepend(
        os.path.join(os.path.dirname(__file__), "..", "scripts"))
    import kvs_scale

    rec = kvs_scale.run(ops=10_000, replicas=3, sessions=512, keys=2048)
    assert rec["completed"] == 10_000 and rec["all_done"]
    assert rec["checked_ok"] is True


def test_submit_batch_basic_checked():
    """The batched public path (round-3 verdict item 5): array-in,
    futures-out, results land in BatchFutures columns; mixed get/put/rmw,
    checked clean."""
    from hermes_tpu.kvs import KVS

    cfg = HermesConfig(n_replicas=3, n_keys=128, n_sessions=16,
                       replay_slots=8, value_words=6, ops_per_session=8,
                       workload=WorkloadConfig(seed=51))
    kvs = KVS(cfg, record=True)
    n = 300
    rng = np.random.default_rng(5)
    kinds = rng.choice([KVS.GET, KVS.PUT, KVS.RMW], size=n).astype(np.int32)
    keys = rng.integers(0, 128, n)
    vals = np.stack([np.arange(n), np.arange(n) * 7], axis=1).astype(np.int32)
    bf = kvs.submit_batch(kinds, keys, vals)
    assert kvs.run_batch(bf, 500)
    assert bf.done_count() == n and bf.all_done()
    # puts carry uids; committed RMWs return the displaced value
    assert (bf.uid[kinds == KVS.PUT] != 0).any()
    c = bf.completion(int(np.nonzero(kinds == KVS.PUT)[0][0]))
    assert c.kind == "put" and c.uid is not None
    assert kvs.rt.check().ok


def test_submit_batch_mixed_with_per_op_api():
    """Batch traffic must coexist with the classic per-op futures: slots
    with queued per-op work keep their FIFO promise (batches skip them)."""
    from hermes_tpu.kvs import KVS

    cfg = HermesConfig(n_replicas=3, n_keys=64, n_sessions=8,
                       replay_slots=4, value_words=5, ops_per_session=8,
                       workload=WorkloadConfig(seed=52))
    kvs = KVS(cfg, record=True)
    f1 = kvs.put(0, 0, 5, [11])
    f2 = kvs.get(1, 3, 5)
    bf = kvs.submit_batch(
        np.full(40, KVS.PUT, np.int32), np.arange(40) % 64,
        np.arange(80, dtype=np.int32).reshape(40, 2))
    assert kvs.run_batch(bf, 300) and kvs.run_until([f1, f2], 100)
    assert f1.result().uid is not None
    assert bf.all_done()
    assert kvs.rt.check().ok


def test_submit_batch_sparse_missing_get():
    """Sparse mode: a batched get of a never-written key completes
    immediately as found=False without claiming a dense slot."""
    from hermes_tpu.kvs import KVS

    cfg = HermesConfig(n_replicas=3, n_keys=32, n_sessions=8,
                       replay_slots=4, value_words=5, ops_per_session=8,
                       workload=WorkloadConfig(seed=53))
    kvs = KVS(cfg, sparse_keys=True)
    wb = kvs.submit_batch(np.array([KVS.PUT], np.int32),
                          np.array([0xDEAD_BEEF_0001], np.uint64),
                          np.array([[9]], np.int32))
    assert kvs.run_batch(wb, 200)  # write resolves BEFORE the gets submit
    kinds = np.array([KVS.GET, KVS.GET], np.int32)
    keys = np.array([0xDEAD_BEEF_0001, 0x5555_5555_5555], np.uint64)
    bf = kvs.submit_batch(kinds, keys)
    assert bf.code[1] != 0 and not bf.found[1]  # absent: done at submit
    assert len(kvs.index) == 1  # the probe claimed no slot
    assert kvs.run_batch(bf, 200)
    assert bf.found[0] and bf.value[0, 0] == 9
    assert bf.future(1).result().found is False


def test_per_op_enqueue_waits_for_batch_owned_slot():
    """A per-op future targeting a slot currently owned by a batch op must
    WAIT (not clobber the in-flight batch stream entry): both the batch op
    and the per-op future resolve (review finding, round 4)."""
    from hermes_tpu.kvs import KVS

    cfg = HermesConfig(n_replicas=3, n_keys=64, n_sessions=4,
                       replay_slots=4, value_words=5, ops_per_session=8,
                       workload=WorkloadConfig(seed=54))
    kvs = KVS(cfg, record=True)
    n = 12
    bf = kvs.submit_batch(
        np.full(n, KVS.PUT, np.int32), np.arange(n) % 64,
        np.arange(2 * n, dtype=np.int32).reshape(n, 2))
    # stall the quorum: frozen replica 2 contributes no acks, so injected
    # writes stay IN FLIGHT and their slots stay batch-owned across rounds
    kvs.freeze(2)
    kvs.step()
    assert (kvs._slot_bid >= 0).any()
    owned = kvs._slot_bid[0, 0] >= 0
    f = kvs.put(0, 0, 7, [99])  # targets a batch-owned slot
    kvs.step()
    if owned:
        assert not f.done()  # waited, did not clobber the batch op
    kvs.rt.thaw(2)
    assert kvs.run_batch(bf, 300)
    assert kvs.run_until([f], 300)
    assert f.result().uid is not None
    assert kvs.rt.check().ok


def test_submit_batch_sharded_backend():
    """The batched client path over the sharded (tpu_ici-shaped) backend:
    array-in futures-out works across the 8-device mesh."""
    import jax
    from jax.sharding import Mesh

    cfg = HermesConfig(n_replicas=8, n_keys=64, n_sessions=4, value_words=6)
    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    kvs = KVS(cfg, backend="sharded", mesh=mesh)
    n = 48
    bf = kvs.submit_batch(
        np.full(n, KVS.PUT, np.int32), np.arange(n) % 64,
        np.arange(2 * n, dtype=np.int32).reshape(n, 2))
    assert kvs.run_batch(bf, 300)
    gets = kvs.submit_batch(np.full(4, KVS.GET, np.int32),
                            np.arange(4))
    assert kvs.run_batch(gets, 300)
    assert gets.all_done()
