"""Round-17 value heap (hermes_tpu/heap): MICA-style variable-length
values behind one packed HEAP_REF word per key.

Covers the declared layout, the host byte<->word codec on adversarial
ragged lengths, the ValueHeap allocator/compactor, analyzer + op-budget
proofs for the device programs, byte-exact end-to-end round trips on
BOTH engines (per-op, batched, multi_get, scan), GC at rebase and under
seeded chaos traffic at pipeline depth 2, snapshot restore with a
torn-heap red test, range migration with extents, the fleet composition,
the serving wire's length-prefixed framing, and the workload size draw.
"""

import dataclasses
import zipfile

import numpy as np
import pytest

from hermes_tpu import heap as H
from hermes_tpu import snapshot
from hermes_tpu.checker import linearizability as lin
from hermes_tpu.config import FleetConfig, HermesConfig, WorkloadConfig
from hermes_tpu.core import layouts
from hermes_tpu.kvs import KVS
from hermes_tpu.transport import codec


def _cfg(**over):
    kw = dict(n_replicas=3, n_keys=128, value_words=3, n_sessions=8,
              replay_slots=8, ops_per_session=64,
              max_value_bytes=256, heap_bytes=1 << 15,
              workload=WorkloadConfig(read_frac=0.5, seed=3))
    kw.update(over)
    return HermesConfig(**kw)


def _pay(i: int, n: int) -> bytes:
    """Deterministic high-bit-heavy payload of length n."""
    return bytes(((i * 37 + j * 151 + 128) & 0xFF) for j in range(n))


def _sharded(cpu_devices, **over):
    from jax.sharding import Mesh

    mesh = Mesh(np.array(cpu_devices[:3]), ("replica",))
    return KVS(_cfg(**over), backend="sharded", mesh=mesh, record="array")


# -- the declared layout -----------------------------------------------------


def test_heap_ref_layout_and_pack_roundtrip():
    f_len = layouts.HEAP_REF.field("len")
    f_gran = layouts.HEAP_REF.field("gran")
    assert f_len.shift == 0 and f_gran.shift == f_len.bits
    # the declared budgets derive from the fields — an edit moves both
    assert layouts.MAX_VALUE_BYTES == f_len.cap - 1
    assert layouts.MAX_HEAP_BYTES == layouts.HEAP_GRANULE * f_gran.cap
    for gran, ln in [(1, 0), (1, 1), (5, 255), (f_gran.cap - 1,
                                                layouts.MAX_VALUE_BYTES)]:
        ref = H.pack_ref(gran, ln)
        assert H.ref_gran(ref) == gran and H.ref_len(ref) == ln
        assert ref > 0  # sign bit stays clear: the word rides int32 columns
        assert ref <= 0x7FFFFFFF


def test_config_validates_heap_mode():
    with pytest.raises(ValueError, match="value_words"):
        HermesConfig(n_replicas=3, n_keys=8, n_sessions=2, value_words=2,
                     max_value_bytes=64)
    with pytest.raises(ValueError, match="granule"):
        _cfg(heap_bytes=(1 << 15) + 1)
    with pytest.raises(ValueError, match="len field|exceeds"):
        _cfg(max_value_bytes=layouts.MAX_VALUE_BYTES + 1)
    with pytest.raises(ValueError, match="two"):
        _cfg(max_value_bytes=256, heap_bytes=layouts.HEAP_GRANULE * 2)
    cfg = _cfg()
    assert cfg.use_heap and cfg.heap_granules == cfg.heap_bytes // 16


# -- the byte<->word codec on adversarial ragged lengths ---------------------

# 0, 1, word-1, word, word+1 — plus mid sizes and the max — with high-bit
# bytes in every position: the exact shear/sign-extension surface.
RAGGED = (0, 1, 3, 4, 5, 7, 8, 9, 63, 64, 65, 255, 256)


@pytest.mark.parametrize("n", RAGGED)
def test_codec_bytes_words_roundtrip_ragged(n):
    rng = np.random.default_rng(n)
    for raw in (bytes([0xFF] * n), bytes([0x80] * n),
                rng.integers(0, 256, n).astype(np.uint8).tobytes()):
        words = codec.bytes_to_words(raw)
        assert words.dtype == np.int32
        assert codec.words_to_bytes(words, len(raw)) == raw
        # fixed-width (config-width) form round-trips identically
        wide = codec.bytes_to_words(raw, n_words=(n + 3) // 4 + 2)
        assert codec.words_to_bytes(wide, len(raw)) == raw


def test_codec_bytes_words_bounds():
    with pytest.raises(ValueError, match="exceed"):
        codec.bytes_to_words(b"x" * 9, n_words=2)
    with pytest.raises(ValueError, match="exceeds"):
        codec.words_to_bytes(np.zeros(1, np.int32), length=5)
    assert codec.words_to_bytes(codec.bytes_to_words(b"")) == b""


def test_codec_rows_words_inverse_and_snapshot_alias():
    rng = np.random.default_rng(7)
    rows8 = rng.integers(-128, 128, size=(5, 3, 16)).astype(np.int8)
    w = codec.rows_to_words(rows8)
    assert w.shape == (5, 3, 4) and w.dtype == np.int32
    np.testing.assert_array_equal(codec.words_to_rows(w), rows8)
    # snapshot.py's historical names alias the ONE implementation
    assert snapshot._rows_to_i32 is codec.rows_to_words
    assert snapshot._i32_to_rows is codec.words_to_rows
    # word composition is little-endian (the device _bank_to_i32 order)
    one = np.array([0x11, 0x22, 0x33, -1], np.int8)
    assert int(codec.rows_to_words(one)[0]) == np.int32(0xFF332211 - (1 << 32))


# -- the workload size draw --------------------------------------------------


def test_value_sizes_deterministic_and_shaped():
    from hermes_tpu.workload.ycsb import (VALUE_SIZE_CLASSES, value_payload,
                                          value_sizes)

    spec = dict(n=4096, max_bytes=1024)
    a = value_sizes(spec, 17)
    b = value_sizes(spec, 17)
    assert a.tobytes() == b.tobytes()  # replay-identical, the chaos rule
    assert a.tobytes() != value_sizes(spec, 18).tobytes()
    assert set(np.unique(a)) <= {c for c in VALUE_SIZE_CLASSES if c <= 1024}
    # memcached shape: the smallest class is the most probable
    counts = {int(c): int((a == c).sum()) for c in np.unique(a)}
    assert counts[16] == max(counts.values())
    assert int(a.max()) <= 1024
    p = value_payload(17, 5, 100)
    assert len(p) == 100 and p == value_payload(17, 5, 100)
    assert p != value_payload(17, 6, 100)
    assert value_payload(17, 5, 0) == b""


def test_make_mix_carries_vlen_and_matrix_values_cell():
    from hermes_tpu.workload.openloop import MixSpec, make_mix, scenario_matrix

    spec = MixSpec(name="values", distribution="zipfian", value_bytes=512)
    mix = make_mix(spec, 64, 256, 9, value_words=1)
    assert "vlen" in mix and int(mix["vlen"].max()) <= 512
    names = [s.name for s in scenario_matrix(value_bytes=512)]
    assert "values" in names
    assert "values" not in [s.name for s in scenario_matrix()]


# -- ValueHeap unit ----------------------------------------------------------


def test_heap_append_read_ragged_and_full():
    heap = H.ValueHeap(_cfg(heap_bytes=1 << 10, max_value_bytes=64))
    refs = {n: heap.append(_pay(n, n)) for n in (0, 1, 15, 16, 17, 64)}
    for n, ref in refs.items():
        assert heap.read(ref) == _pay(n, n)
    with pytest.raises(ValueError, match="max_value_bytes"):
        heap.append(b"x" * 65)
    with pytest.raises(H.HeapFull):
        for _ in range(64):
            heap.append(b"y" * 64)
    with pytest.raises(ValueError, match="dangling"):
        heap.read(H.pack_ref(heap._cursor + 1, 4))


def test_heap_compact_remap_and_unrooted_ref():
    heap = H.ValueHeap(_cfg(heap_bytes=1 << 12, max_value_bytes=64))
    live, dead = [], []
    for i in range(12):
        dead.append(heap.append(_pay(i, 40)))       # overwritten
        live.append(heap.append(_pay(100 + i, 33)))  # survives
    used0 = heap.used_bytes()
    old, new = heap.compact(np.asarray(live, np.int64))
    remapped = H.ValueHeap.remap(np.asarray(live, np.int64), old, new)
    for i, ref in enumerate(remapped):
        assert heap.read(int(ref)) == _pay(100 + i, 33)
    assert heap.used_bytes() < used0
    assert heap.stats()["util"] is not None
    assert heap.live_bytes == 33 * 12
    # null refs stay null; an unrooted ref must raise, never survive
    assert H.ValueHeap.remap(np.zeros(3, np.int64), old, new).sum() == 0
    with pytest.raises(ValueError, match="root"):
        H.ValueHeap.remap(np.asarray([dead[0]], np.int64), old, new)


def test_heap_device_gather_matches_mirror_and_clamps():
    heap = H.ValueHeap(_cfg())
    refs = [heap.append(_pay(i, n)) for i, n in enumerate(RAGGED)]
    rows, lens = heap.device_gather(np.asarray(refs, np.int32))
    for i, n in enumerate(RAGGED):
        assert int(lens[i]) == n
        assert rows[i, :n].tobytes() == _pay(i, n)
        assert not rows[i, n:].any()  # masked past the extent: no leaks
    # untrusted refs clamp in bounds instead of faulting (wire-clamp rule)
    hostile = np.asarray([H.pack_ref(heap.granules - 1, 256), -1], np.int32)
    rows, lens = heap.device_gather(hostile)
    assert rows.shape[1] == heap.cap


# -- analyzer + op budget ----------------------------------------------------


def test_heap_gather_analyzer_clean_and_census_budget():
    import json

    cfg = _cfg()
    assert H.analyze_gather(cfg, batch=256) == []
    g = H.gather_census(cfg, batch=256)
    a = H.append_census(cfg, chunk=1024)
    with open("OP_BUDGET.json") as f:
        budget = json.load(f)
    for name, cen in (("heap_path", g), ("heap_append", a)):
        for k, ceiling in budget[name].items():
            assert cen[k] <= ceiling, (name, k, cen[k], ceiling)
    assert g["sparse_total"] == 1   # ONE gather answers the whole batch
    assert a["sparse_total"] == 0   # the append is dense


# -- KVS end to end (both engines) -------------------------------------------


def _roundtrip_kvs(kvs):
    n = 48
    keys = np.arange(n, dtype=np.int64)
    pays = [_pay(i, (i * 7) % 200) for i in range(n)]
    bf = kvs.submit_batch(np.full(n, KVS.PUT, np.int32), keys, pays)
    assert kvs.run_batch(bf)
    res = kvs.multi_get(keys)
    assert res.all_done()
    assert all(res.data[i] == pays[i] for i in range(n))
    sc = kvs.scan(0, n)
    assert sc.all_done()
    assert all(sc.data[i] == pays[i] for i in range(n))
    # batched completions carry the bytes too
    c = bf.future(3).result()
    assert c.uid is not None
    return keys, pays


def test_kvs_batched_put_get_scan_byte_exact():
    kvs = KVS(_cfg(), record=True)
    keys, pays = _roundtrip_kvs(kvs)
    # per-op path: put/get/rmw completions carry .data
    f = kvs.put(0, 0, 7, b"\x00\x80\xff new")
    assert kvs.run_until([f])
    g = kvs.get(0, 0, 7)
    assert kvs.run_until([g])
    assert g.result().data == b"\x00\x80\xff new"
    r = kvs.rmw(0, 1, 7, b"after-rmw")
    assert kvs.run_until([r])
    c = r.result()
    if c.kind == "rmw":  # read-part: the displaced bytes
        assert c.data == b"\x00\x80\xff new"
        g = kvs.get(0, 0, 7)
        assert kvs.run_until([g])
        assert g.result().data == b"after-rmw"
    assert kvs.rt.check().ok
    assert lin.stale_read(kvs.rt.history_ops()) == []


def test_kvs_rejects_word_payloads_in_heap_mode():
    kvs = KVS(_cfg())
    with pytest.raises(TypeError, match="byte payloads"):
        kvs.put(0, 0, 1, [1, 2])
    with pytest.raises(TypeError, match="byte payloads"):
        kvs.submit_batch(np.full(2, KVS.PUT, np.int32),
                         np.asarray([1, 2], np.int64), [b"ok", [3]])
    with pytest.raises(ValueError, match="max_value_bytes"):
        kvs.put(0, 0, 1, b"z" * 257)
    # an update batch without payloads would commit null refs — refused
    with pytest.raises(TypeError, match="values=None"):
        kvs.submit_batch(np.full(2, KVS.PUT, np.int32),
                         np.asarray([1, 2], np.int64))
    # a read-only batch legitimately carries no values
    bf = kvs.submit_batch(np.full(2, KVS.GET, np.int32),
                          np.asarray([1, 2], np.int64))
    assert kvs.run_batch(bf)


def test_kvs_sharded_put_get_scan_byte_exact(cpu_devices):
    kvs = _sharded(cpu_devices)
    _roundtrip_kvs(kvs)
    assert kvs.rt.check().ok
    assert lin.stale_read(kvs.rt.history_ops()) == []


# -- GC ----------------------------------------------------------------------


def test_heap_gc_on_pressure_and_explicit():
    # a heap sized to force collection mid-load: overwrite churn must
    # stay serviceable, with every surviving value byte-exact
    kvs = KVS(_cfg(n_keys=32, heap_bytes=1 << 12, max_value_bytes=128),
              record=True)
    rng = np.random.default_rng(5)
    latest = {}
    for round_ in range(12):
        keys = rng.permutation(32)[:16].astype(np.int64)
        pays = [_pay(round_ * 64 + int(k), int(rng.integers(1, 128)))
                for k in keys]
        bf = kvs.submit_batch(np.full(16, KVS.PUT, np.int32), keys, pays)
        assert kvs.run_batch(bf)
        for k, p in zip(keys, pays):
            latest[int(k)] = p
    assert kvs.heap.gc_runs >= 1, "churn never triggered a pressure GC"
    st = kvs.heap_gc(reason="test")
    assert st and st["live_bytes"] <= st["used_bytes"]
    res = kvs.multi_get(np.asarray(sorted(latest), np.int64))
    assert res.all_done()
    for j, k in enumerate(sorted(latest)):
        assert res.data[j] == latest[k], k
    assert kvs.rt.check().ok


def test_heap_gc_rides_version_rebase():
    kvs = KVS(_cfg())
    bf = kvs.submit_batch(np.full(8, KVS.PUT, np.int32),
                          np.arange(8, dtype=np.int64),
                          [_pay(i, 20) for i in range(8)])
    assert kvs.run_batch(bf)
    # overwrite: half the extents die
    bf = kvs.submit_batch(np.full(8, KVS.PUT, np.int32),
                          np.arange(8, dtype=np.int64),
                          [_pay(100 + i, 24) for i in range(8)])
    assert kvs.run_batch(bf)
    runs0 = kvs.heap.gc_runs
    assert kvs.rt.rebase_versions() >= 0
    assert kvs.heap.gc_runs == runs0 + 1, "rebase did not drive the GC"
    res = kvs.multi_get(np.arange(8, dtype=np.int64))
    assert res.all_done()
    assert all(res.data[i] == _pay(100 + i, 24) for i in range(8))


@pytest.mark.parametrize("engine", ["batched", "sharded"])
def test_gc_under_chaos_traffic_depth2(engine, cpu_devices):
    """Satellite: seeded chaos schedule at pipeline depth 2, rebase-GC
    runs MID-LOAD on both engines — checker green, values byte-exact
    after compaction, stale_read == []."""
    from jax.sharding import Mesh

    from hermes_tpu import chaos as chaos_lib

    cfg = _cfg(pipeline_depth=2, n_keys=64, heap_bytes=1 << 13,
               max_value_bytes=128)
    if engine == "sharded":
        mesh = Mesh(np.array(cpu_devices[:3]), ("replica",))
        kvs = KVS(cfg, backend="sharded", mesh=mesh, record="array")
    else:
        kvs = KVS(cfg, record=True)
    rng = np.random.default_rng(23)
    lines, step = [], 0
    for _ in range(3):
        r = int(rng.integers(0, cfg.n_replicas))
        fr = step + int(rng.integers(1, 4))
        th = fr + int(rng.integers(3, 6))
        lines += [f"@{fr} freeze {r}", f"@{th} thaw {r}"]
        step = th + 2
    runner = chaos_lib.ChaosRunner(kvs, chaos_lib.Schedule.parse(
        "\n".join(lines)))
    latest = {}
    gcs = 0
    for i in range(30):
        runner.tick(i)
        keys = rng.permutation(cfg.n_keys)[:8].astype(np.int64)
        pays = [_pay(i * 101 + int(k), int(rng.integers(0, 120)))
                for k in keys]
        bf = kvs.submit_batch(np.full(8, KVS.PUT, np.int32), keys, pays)
        assert kvs.run_batch(bf, max_steps=2000)
        for k, p in zip(keys, pays):
            latest[int(k)] = p
        if i in (9, 19):  # rebase-GC mid-load (frozen windows included)
            if kvs.heap_gc(reason="chaos-test"):
                gcs += 1
    for r in range(cfg.n_replicas):
        kvs.rt.thaw(r)
    kvs.rt.flush_pipeline()
    kvs.flush()
    assert gcs >= 1, "no mid-load GC completed (schedule left none viable)"
    res = kvs.multi_get(np.asarray(sorted(latest), np.int64))
    assert res.all_done()
    for j, k in enumerate(sorted(latest)):
        assert res.data[j] == latest[k], k
    assert kvs.rt.check().ok
    assert lin.stale_read(kvs.rt.history_ops()) == []


# -- snapshot ----------------------------------------------------------------


def test_snapshot_roundtrip_and_torn_heap_red(tmp_path):
    kvs = KVS(_cfg())
    n = 24
    pays = [_pay(i, (i * 11) % 200) for i in range(n)]
    bf = kvs.submit_batch(np.full(n, KVS.PUT, np.int32),
                          np.arange(n, dtype=np.int64), pays)
    assert kvs.run_batch(bf)
    p = str(tmp_path / "heap.npz")
    snapshot.save(p, kvs)

    tgt = KVS(_cfg())
    snapshot.load(p, tgt)
    res = tgt.multi_get(np.arange(n, dtype=np.int64))
    assert res.all_done()
    assert all(res.data[i] == pays[i] for i in range(n))

    # red: a bit-flipped heap log must reject on the manifest checksum —
    # a torn heap blob is a torn snapshot, never silently served
    torn = str(tmp_path / "torn.npz")
    with zipfile.ZipFile(p) as zin, zipfile.ZipFile(torn, "w") as zout:
        for name in zin.namelist():
            data = bytearray(zin.read(name))
            if name.startswith("kvs.heap.log"):
                data[len(data) // 2] ^= 0xFF
            zout.writestr(name, bytes(data))
    with pytest.raises(ValueError, match="checksum|torn"):
        snapshot.load(torn, KVS(_cfg()))

    # red: a heap-mode target rejects an archive missing the heap section
    word_cfg = _cfg(max_value_bytes=0)
    word = KVS(word_cfg)
    pw = str(tmp_path / "word.npz")
    snapshot.save(pw, word)
    with pytest.raises(ValueError, match="heap|missing|fingerprint"):
        snapshot.load(pw, KVS(_cfg()))


# -- migration ---------------------------------------------------------------


def test_migrate_range_moves_extents_byte_exact():
    from hermes_tpu.elastic import migrate_range

    src, dst = KVS(_cfg()), KVS(_cfg())
    n = 48
    pays = [_pay(i, (i * 13) % 180) for i in range(n)]
    bf = src.submit_batch(np.full(n, KVS.PUT, np.int32),
                          np.arange(n, dtype=np.int64), pays)
    assert src.run_batch(bf)
    s = migrate_range(src, dst, 8, 40)
    assert s["heap_extents"] == 32
    res = dst.multi_get(np.arange(8, 40, dtype=np.int64))
    assert res.all_done()
    assert all(res.data[j] == pays[8 + j] for j in range(32))
    # destination refs are its OWN granules: its mirror serves them
    assert dst.heap.appends >= 32


def test_migrate_refuses_heap_mode_mismatch():
    from hermes_tpu.elastic import migrate_range

    src = KVS(_cfg())
    dst = KVS(_cfg(max_value_bytes=0, value_words=3))
    with pytest.raises(ValueError, match="heap"):
        migrate_range(src, dst, 0, 8)
    small = KVS(_cfg(max_value_bytes=128))
    with pytest.raises(ValueError, match="cannot hold"):
        migrate_range(src, small, 0, 8)


# -- fleet -------------------------------------------------------------------


def test_fleet_heap_roundtrip_and_cross_group_migration():
    from hermes_tpu.fleet import Fleet

    base = _cfg(n_keys=48, n_sessions=4, replay_slots=4,
                max_value_bytes=128, heap_bytes=1 << 14)
    fleet = Fleet(FleetConfig(groups=2, base=base,
                              ranges=((0, 32), (32, 64))), record=True)
    keys = np.arange(40, dtype=np.int64)
    pays = [_pay(i, (i * 5) % 120) for i in range(40)]
    fb = fleet.submit_batch(np.full(40, KVS.PUT, np.int32), keys, pays)
    for _ in range(4000):
        if fb.all_done():
            break
        fleet.step()
    assert fb.all_done()
    res = fleet.multi_get(keys)
    for _ in range(4000):
        if res.all_done():
            break
        fleet.step()
    assert res.all_done()
    assert all(res.data[i] == pays[i] for i in range(40))
    s = fleet.migrate(0, 8, 1)
    assert s["heap_extents"] == 8
    res = fleet.multi_get(keys)
    for _ in range(4000):
        if res.all_done():
            break
        fleet.step()
    assert res.all_done()
    assert all(res.data[i] == pays[i] for i in range(40))
    assert fleet.check()["ok"]


# -- serving wire ------------------------------------------------------------


def test_wire_heap_request_response_roundtrip():
    from hermes_tpu.serving import wire

    vb = 256
    for data in (None, b"", b"\x00", b"\xff" * vb):
        req = wire.Request(kind="put", req_id=3, tenant=1, key=9, data=data)
        out = wire.decode_request(wire.encode_request(req, 1, vb), 1, vb)
        assert out.data == data and out.key == 9
    # a get's tail is always empty on the wire
    g = wire.Request(kind="get", req_id=4, tenant=0, key=2, data=b"junk")
    assert wire.decode_request(wire.encode_request(g, 1, vb), 1, vb).data \
        is None
    rsp = wire.Response(status=wire.S_OK, req_id=3, found=True,
                        uid=(1, 2), data=b"\x80abc")
    out = wire.decode_response(wire.encode_response(rsp, 1, vb), 1, vb)
    assert out.data == b"\x80abc" and out.uid == (1, 2)
    # None (never written) survives distinct from b"" (a real empty value)
    for data in (None, b""):
        rsp = wire.Response(status=wire.S_OK, req_id=5, found=True, data=data)
        assert wire.decode_response(
            wire.encode_response(rsp, 1, vb), 1, vb).data == data


def test_wire_heap_read_response_rows_and_adversarial():
    from hermes_tpu.serving import wire

    vb = 256
    rr = wire.ReadResponse(status=wire.S_OK, req_id=1,
                           found=[True, True, False],
                           local=[True, False, False],
                           codes=[wire.RK_OK] * 3,
                           data=[b"\xffhi", b"", None])
    buf = wire.encode_read_response(rr, 1, vb)
    assert len(buf) == wire.rrsp_nbytes(1, 3, vb)
    out = wire.decode_read_response(buf, 1, vb)
    assert out.data == [b"\xffhi", b"", None]
    assert out.found == [True, True, False]
    # adversarial: truncated tail / oversized dlen refuse loudly
    req = wire.Request(kind="put", req_id=1, tenant=0, key=1, data=b"abcd")
    enc = wire.encode_request(req, 1, vb)
    with pytest.raises(ValueError, match="truncated|size|declares"):
        wire.decode_request(enc[:-2], 1, vb)
    import struct

    bad = enc[:wire._REQ.size] + struct.pack("<I", vb + 1) + b"x" * (vb + 1)
    with pytest.raises(ValueError, match="declares"):
        wire.decode_request(bad, 1, vb)


def test_serving_loopback_heap_end_to_end():
    from hermes_tpu.serving import wire
    from hermes_tpu.serving.rpc import LoopbackServer
    from hermes_tpu.serving.server import Frontend
    from hermes_tpu.serving.soak import committed_uids

    kvs = KVS(_cfg(), record=True)
    fe = Frontend(kvs)
    lb = LoopbackServer(fe)

    def drive(req):
        rsp = lb.submit(req)
        if rsp is not None:
            return rsp
        for _ in range(400):
            out = lb.pump()
            if out:
                return out[0]
        raise AssertionError("no response")

    pays = {k: _pay(k, 10 + 17 * k) for k in (1, 2, 3)}
    for rid, (k, p) in enumerate(pays.items(), start=1):
        rsp = drive(wire.Request(kind="put", req_id=rid, tenant=0, key=k,
                                 data=p))
        assert rsp.status == wire.S_OK and rsp.uid is not None
    rsp = drive(wire.Request(kind="get", req_id=10, tenant=0, key=2))
    assert rsp.data == pays[2]
    rsp = drive(wire.ReadRequest(kind="mget", req_id=11, tenant=0,
                                 keys=[1, 3, 5]))
    assert rsp.data[0] == pays[1] and rsp.data[1] == pays[3]
    assert rsp.data[2] is None  # never written
    rsp = drive(wire.ReadRequest(kind="scan", req_id=12, tenant=0,
                                 lo=1, hi=4))
    assert rsp.data == [pays[1], pays[2], pays[3]]
    # the response-log walker handles variable heap-mode records
    assert len(committed_uids(fe, lb)) == 3
    # an update without a payload is refused at the door
    rsp = drive(wire.Request(kind="put", req_id=13, tenant=0, key=1))
    assert rsp.status == wire.S_REJECTED
    assert kvs.rt.check().ok
