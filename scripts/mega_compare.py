"""Mega-round vs fused-sort A/B at the exact bench shape (round-15
tentpole evidence): one process, one chip claim, every cell through
bench.run_mix's measurement protocol — the scripts/fused_compare.py
pattern, with ``over=dict(mega_round=...)`` as the toggle.

The modeled projection (SHARDED_CENSUS.json ``mega_projection``) brackets
the mega path between ~0.54x and ~2.1x of the 13.7M w/s plateau because
the serial kernel-interior cost (~2-12 ns/iteration over ~1.6M
iterations/round) is the decisive unknown the CPU host cannot measure —
THIS script is the required evidence.  Cells: the primary YCSB-A mix and
the contended zipfian mix, mega on/off; the off cells ARE the bench
operating point, so the pair is directly comparable to BENCH_r05.json.

Writes MEGA_COMPARE.json and prints one JSON line per cell to stderr,
plus a summary line to stdout.  Run on the real chip (default env, no
other TPU process, no timeout-kill).
"""

import json
import os
import sys
import time

sys.path.insert(0, ".")

import bench

CELLS = [
    ("a", {"mega_round": True}),
    ("a", {"mega_round": False}),
    ("zipfian", {"mega_round": True}),
    ("zipfian", {"mega_round": False}),
]


def main() -> None:
    ok, info = bench.probe_backend(
        float(os.environ.get("HERMES_BENCH_PROBE_TIMEOUT", "180")))
    if not ok:
        print(json.dumps({"error": info}))
        sys.exit(1)

    results = []
    for mix, over in CELLS:
        t0 = time.perf_counter()
        r = bench.run_mix(mix, over=over)
        r["mega_round"] = over["mega_round"]
        r["cell_wall_s"] = round(time.perf_counter() - t0, 1)
        results.append(r)
        print(json.dumps(r), file=sys.stderr, flush=True)
        # rewrite after every cell: a mid-matrix chip failure must not
        # discard the completed cells' artifact
        with open("MEGA_COMPARE.json", "w") as f:
            json.dump(results, f, indent=1)

    summary = {}
    for r in results:
        summary.setdefault(r["mix"], {})[
            "mega" if r["mega_round"] else "fused"] = dict(
                writes_per_sec=r["writes_per_sec"], round_us=r["round_us"])
    for mix, cells in summary.items():
        if "mega" in cells and "fused" in cells:
            cells["round_ms_saved"] = round(
                (cells["fused"]["round_us"] - cells["mega"]["round_us"])
                / 1e3, 2)
            cells["speedup_x"] = round(
                cells["fused"]["round_us"]
                / max(1e-9, cells["mega"]["round_us"]), 3)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
