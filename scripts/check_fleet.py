"""Round-13 fleet gate (CI, the EIGHTH gate): pod-scale key-sharded
protocol groups must hold their contracts on every change.

Four assertions, CPU-smoke sized (joins the census / obs-overhead /
analysis / pipeline / chaos / elastic / netchaos gates in
scripts/run_gates.py — the EIGHT gates run SERIALLY, never beside
pytest: the obs-overhead gate is contention-sensitive):

  1. fleet soak — a 4-group fleet at pipeline depth 2 serves a standing
     client mix spanning every group's range on BOTH engines (batched:
     groups round-robin over the host devices; sharded: 4 groups x 2
     replicas on DISJOINT submeshes of the 8-device grid —
     launch.fleet_meshes), every op resolves exactly once (totals
     conservation), the linearizability checker is green in EVERY group,
     and verify_fleet proves the cross-group invariants (routing
     injectivity, migration-uid namespaces, group-scoped membership);
  2. one-group rolling drill — group 0 is rolling-crash-restarted under
     fleet-wide load while groups 1-3 must stay untouched (never frozen,
     never removed) AND keep committing in every sampled window; the
     per-group dip is recorded;
  3. deterministic replay — the same seed + FleetConfig replays a
     fleet-wide seeded chaos schedule to byte-identical per-group
     executed logs and final state trees;
  4. scale-out floor — a 4-group fleet's aggregate committed-writes/s
     (sum of per-group cells, each measured alone — the dedicated-
     hardware capacity the on-chip rerun measures) sustains >= 3x the
     single-group cell at the same per-group shape; the honest
     concurrent-dispatch cell is recorded alongside (this host has ~2
     cores; on the (groups, replicas) pod grid concurrent == aggregate).

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/check_fleet.py

Prints one JSON line (also written to FLEET_SOAK.json); exit non-zero on
any violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

SEED = 13
GROUPS = 4


def _fcfg(n_replicas=4, **over):
    from hermes_tpu.config import FleetConfig, HermesConfig, WorkloadConfig

    kw = dict(
        n_replicas=n_replicas, n_keys=64, n_sessions=4, replay_slots=6,
        ops_per_session=96, value_words=6, replay_age=6,
        replay_scan_every=4, rebroadcast_every=2, lease_steps=6,
        pipeline_depth=2,
        workload=WorkloadConfig(read_frac=0.4, rmw_frac=0.2, seed=SEED),
    )
    kw.update(over)
    return FleetConfig(groups=GROUPS, base=HermesConfig(**kw))


def _mix(fcfg, n, seed=SEED):
    import numpy as np

    from hermes_tpu.fleet import Fleet

    rng = np.random.default_rng(seed)
    keys = rng.integers(0, fcfg.total_keys, size=n).astype(np.int64)
    kinds = np.where(rng.random(n) < 0.4, Fleet.GET, Fleet.PUT).astype(
        np.int32)
    values = rng.integers(0, 1 << 20, size=(
        n, fcfg.base.value_words - 2)).astype(np.int32)
    return kinds, keys, values


def check_soak(report: dict) -> None:
    import numpy as np

    from hermes_tpu import launch
    from hermes_tpu.fleet import Fleet, verify_fleet

    for backend in ("batched", "sharded"):
        if backend == "batched":
            fcfg = _fcfg()
            fleet = Fleet(fcfg, record=True, detect=3)
        else:
            fcfg = _fcfg(n_replicas=2)
            fleet = Fleet(fcfg, backend="sharded",
                          meshes=launch.fleet_meshes(GROUPS, 2),
                          record=True, detect=3)
        n = 400
        kinds, keys, values = _mix(fcfg, n)
        fb = fleet.submit_batch(kinds, keys, values)
        spanned = sorted({int(g) for g in fb.group if g >= 0})
        assert spanned == list(range(GROUPS)), (
            f"{backend}: mix spanned only groups {spanned}")
        assert fleet.run_batch(fb), f"{backend}: fleet mix stranded " \
            f"{n - fb.done_count()} op(s)"
        assert fb.done_count() == n  # totals conservation
        from hermes_tpu.kvs import C_LOST, C_REJECTED

        codes = np.asarray(fb.code)
        assert not ((codes == C_LOST) | (codes == C_REJECTED)).any(), (
            f"{backend}: clean soak lost/rejected ops")
        v = fleet.check()
        assert v["ok"], f"{backend}: checker FAIL {v}"
        ev = verify_fleet(fleet)
        report[f"{backend}_soak"] = dict(
            ops=n, groups=GROUPS, checked_ok=True,
            group_verdicts=v["groups"], fleet_invariants=ev)


def check_group_drill(report: dict) -> None:
    import numpy as np

    from hermes_tpu import chaos
    from hermes_tpu.fleet import Fleet, FleetChaosRunner

    fcfg = _fcfg()
    fleet = Fleet(fcfg, record=True, detect=3)
    cfg0 = fcfg.group_cfg(0)
    start, spacing = 4, 10
    sched0 = chaos.Schedule.rolling_restart(cfg0, start=start,
                                            spacing=spacing)
    steps = start + spacing * cfg0.n_replicas + spacing
    n_ops = steps * GROUPS * cfg0.n_replicas * cfg0.n_sessions
    kinds, keys, values = _mix(fcfg, n_ops)
    fb = fleet.submit_batch(kinds, keys, values)
    runner = FleetChaosRunner(
        fleet, [sched0] + [chaos.Schedule([])] * (GROUPS - 1),
        spec=chaos.ChaosSpec(min_healthy=2))

    window = spacing
    others_fenced = []
    samples = []  # per window: per-group cumulative commits

    def commits():
        return [int(c["n_write"] + c["n_rmw"])
                for c in fleet.counters()["groups"]]

    def on_step(step):
        others_fenced.append(any(
            fleet.groups[g].rt.frozen.any()
            or int(fleet.groups[g].rt.live[0])
            != fleet.groups[g].cfg.full_mask
            for g in range(1, GROUPS)))
        if (step + 1) % window == 0:
            samples.append(commits())

    runner.on_step = on_step
    res = runner.run(steps, heal=True, check=True)
    fleet.run_batch(fb)

    assert not any(others_fenced), (
        "the group-0 drill fenced a replica in another group")
    restarts = sum(1 for e in runner.runners[0].log
                   if e["kind"] == "crash_restart")
    assert restarts == cfg0.n_replicas, (
        f"only {restarts}/{cfg0.n_replicas} group-0 restarts applied")
    assert res["checked_ok"], res.get("group_verdicts")
    deltas = np.diff(np.asarray(samples), axis=0)  # (windows, groups)
    assert (deltas[:, 1:] > 0).all(), (
        "a non-drilled group stopped committing during the drill: "
        f"{deltas.tolist()}")
    per_group_dip = []
    for g in range(GROUPS):
        best = int(deltas[:, g].max())
        worst = int(deltas[:, g].min())
        per_group_dip.append(dict(
            group=g, worst_window_commits=worst, best_window_commits=best,
            dip_pct=round(100.0 * (1 - worst / max(1, best)), 1)))
    report["group0_rolling_drill"] = dict(
        restarts=restarts, steps=steps, checked_ok=True,
        lost_ops=res["lost_ops"], per_group_dip=per_group_dip,
        others_never_fenced=True)


def check_replay(report: dict) -> None:
    import jax
    import numpy as np

    from hermes_tpu import chaos
    from hermes_tpu.fleet import Fleet, FleetChaosRunner, fleet_schedules

    fcfg = _fcfg()
    outs = []
    for _ in range(2):
        fleet = Fleet(fcfg, record=True, detect=2)
        kinds, keys, values = _mix(fcfg, 120, seed=SEED + 1)
        fb = fleet.submit_batch(kinds, keys, values)
        runner = FleetChaosRunner(
            fleet, fleet_schedules(fcfg, seed=SEED, steps=20),
            spec=chaos.ChaosSpec(min_healthy=2))
        res = runner.run(20, check=True)
        assert res["checked_ok"], res
        fleet.run_batch(fb)
        states = [jax.tree.leaves(jax.device_get(g.rt.fs))
                  for g in fleet.groups]
        outs.append((runner.log_json(), states))
    assert outs[0][0] == outs[1][0], "fleet executed logs differ"
    for ga, gb in zip(outs[0][1], outs[1][1]):
        for a, b in zip(ga, gb):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    report["deterministic_replay"] = True


def check_scaleout(report: dict) -> None:
    from hermes_tpu.config import FleetConfig, HermesConfig, WorkloadConfig
    from hermes_tpu.fleet.bench import run_fleet_cells

    base = HermesConfig(
        n_replicas=8, n_keys=1 << 14, n_sessions=1024, replay_slots=64,
        ops_per_session=256, value_words=8, wrap_stream=True,
        device_stream=True, arb_mode="sort", chain_writes=128,
        lane_budget_cfg=768, read_unroll=2, rebroadcast_every=4,
        replay_scan_every=32, workload=WorkloadConfig(read_frac=0.5))
    cells = run_fleet_cells(FleetConfig(groups=GROUPS, base=base),
                            rounds=10, chunks=3)
    assert cells["scaleout_x"] >= 3.0, (
        f"4-group aggregate is only {cells['scaleout_x']}x the "
        f"single-group cell "
        f"({cells['aggregate_writes_per_sec']} vs "
        f"{cells['single_group']['writes_per_sec']} writes/s)")
    report["scaleout"] = cells


def main() -> int:
    report: dict = {"gate": "fleet"}
    try:
        check_soak(report)
        check_group_drill(report)
        check_replay(report)
        check_scaleout(report)
    except AssertionError as e:
        report["ok"] = False
        report["error"] = str(e)
        print(json.dumps(report, default=str))
        return 1
    report["ok"] = True
    out = os.path.join(os.path.dirname(__file__), "..", "FLEET_SOAK.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True, default=str)
        f.write("\n")
    print(json.dumps(report, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
