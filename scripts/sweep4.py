"""Round-4 on-chip sweep around the bench operating point UNDER THE SORT
ARBITER (the round-4 default): the race-tuned shape (S=32768, lane=24576,
read_unroll=2) was re-swept because the sort arbiter's cost structure
differs (one sort + scatter vs scatter-min + gather).  Cells: (n_sessions
x lane_budget), read_unroll at the best shape, and chain depth for the
contended zipfian mix (version burn at deeper chains is bounded by the
cell-runner's watermark guard; sustained runs use the runtime
auto-rebase).

Every cell runs through ``bench.run_mix`` with ``over`` shape overrides,
so the sweep measures exactly what bench.py runs.

Usage (chip, default env, ONE process):  python scripts/sweep4.py
Prints one JSON line per cell; writes SWEEP4.json.
"""

import json
import sys

sys.path.insert(0, ".")

import bench


def run_cell(mix="a", S=32768, C=None, ru=2, chain=128):
    over = dict(n_sessions=S, lane_budget_cfg=C or (3 * S) // 4,
                read_unroll=ru, arb_mode="sort", chain_writes=chain)
    r = bench.run_mix(mix, over=over, chunks=2)
    rec = dict(mix=mix, S=S, C=over["lane_budget_cfg"], read_unroll=ru,
               chain=chain, wps=r["writes_per_sec"],
               round_ms=round(r["round_us"] / 1e3, 2))
    print(json.dumps(rec), flush=True)
    return rec


def main():
    out = []
    # shape sweep, uniform mix, sort+chain128
    for S, C in ((16384, 12288), (32768, 16384), (32768, 24576),
                 (32768, 32768), (65536, 24576), (65536, 49152)):
        out.append(run_cell(S=S, C=C))
    best = max(out, key=lambda r: r["wps"])
    # read_unroll at the best shape
    for ru in (1, 3, 4):
        out.append(run_cell(S=best["S"], C=best["C"], ru=ru))
    # chain depth on the contended mix
    for ch in (64, 128, 256, 512, 1024):
        out.append(run_cell(mix="zipfian", chain=ch))
    with open("SWEEP4.json", "w") as f:
        json.dump(out, f, indent=1)

    # the follow-up cells that pinned the production defaults (SWEEP4B):
    # larger uniform shapes (98304 gains <1% over 65536, 131072 rolls
    # off), deeper contended chains (2048 is the plateau; 4096 flat), and
    # the zipfian shape preference (bigger S measurably hurts at depth)
    ext = []
    for S, C in ((98304, 73728), (131072, 98304)):
        ext.append(run_cell(S=S, C=C))
    for ch in (2048, 4096):
        ext.append(run_cell(mix="zipfian", chain=ch))
    ext.append(run_cell(mix="zipfian", S=65536, C=49152, chain=1024))
    ext.append(run_cell(mix="zipfian", S=65536, C=49152, chain=4096))
    with open("SWEEP4B.json", "w") as f:
        json.dump(ext, f, indent=1)


if __name__ == "__main__":
    main()
