"""Round-17 value-heap gate (CI, the TENTH gate): variable-length values
are a storage layer under the whole stack, so the gate proves the layer
end to end, CPU-smoke sized (joins the nine earlier gates in
scripts/run_gates.py — gates run SERIALLY, never beside pytest):

  1. heap soak, both engines — seeded memcached-shaped overwrite churn
     (ycsb.value_sizes) at pipeline depth 2 against a DELIBERATELY small
     log, so allocation-pressure GC and an explicit rebase-boundary GC
     both fire mid-load: every surviving value must read back byte-exact
     (multi_get AND the raw device extent gather, cross-checked against
     the host mirror), the linearizability checker stays green with
     ``stale_read == []``, and post-compaction utilization (live bytes /
     allocated prefix) must hold the UTIL_FLOOR — the bounded-heap
     proof: compaction actually reclaims, the log cannot creep;
  2. fleet migration with extents — a 2-group heap-mode fleet moves a
     live range between groups: the extents must re-appear byte-exact
     behind the destination group's OWN refs, and the fleet checker +
     invariants must hold;
  3. torn-heap-snapshot red test — a clean snapshot restores every
     payload byte-exact, and the SAME archive with one bit flipped in
     the heap log member must REFUSE to load on its manifest checksum
     (a torn heap is a torn snapshot, never silently served);
  4. census-unchanged — the write-round programs of a heap-mode config
     must lower to EXACTLY the same op census as the fixed-word config
     (batched 12 / sharded 15 sparse, mega 4 / 7 — the protocol carries
     only the packed HEAP_REF word, the extent lands before the INV
     issues), and the heap's own dispatches must hold their OP_BUDGET
     sections (heap_path: ONE gather; heap_append: zero sparse ops).

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/check_heap.py

Prints one JSON line (also written to HEAP_SOAK.json); exit non-zero on
any violation.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import zipfile

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

SEED = 17
#: Post-compaction utilization floor: live bytes over the allocated
#: prefix.  Compaction packs extents back-to-back, so the only slack is
#: granule rounding (< 16 bytes per extent) — 0.75 leaves margin for a
#: small-value draw while still catching a compactor that leaks extents.
UTIL_FLOOR = 0.75


def _cfg(**over):
    from hermes_tpu.config import HermesConfig, WorkloadConfig

    kw = dict(
        n_replicas=3, n_keys=64, n_sessions=8, replay_slots=8,
        ops_per_session=96, value_words=3, pipeline_depth=2,
        max_value_bytes=256, heap_bytes=1 << 13,
        workload=WorkloadConfig(read_frac=0.5, seed=SEED),
    )
    kw.update(over)
    return HermesConfig(**kw)


def _store(backend: str):
    from hermes_tpu.kvs import KVS

    if backend == "sharded":
        import jax
        import numpy as np
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:3]), ("replica",))
        return KVS(_cfg(), backend="sharded", mesh=mesh, record="array")
    return KVS(_cfg(), record=True)


def check_heap_soak(report: dict) -> None:
    import numpy as np

    from hermes_tpu.checker import linearizability as lin
    from hermes_tpu.kvs import KVS
    from hermes_tpu.workload.ycsb import value_payload, value_sizes

    for backend in ("batched", "sharded"):
        store = _store(backend)
        cfg = store.cfg
        rng = np.random.default_rng(SEED)
        latest = {}
        rounds = 24
        per = 16
        lens = value_sizes(dict(n=rounds * per,
                                max_bytes=cfg.max_value_bytes), SEED)
        for r in range(rounds):
            keys = rng.permutation(cfg.n_keys)[:per].astype(np.int64)
            pays = [value_payload(SEED, r * per + j, int(lens[r * per + j]))
                    for j in range(per)]
            bf = store.submit_batch(np.full(per, KVS.PUT, np.int32),
                                    keys, pays)
            assert store.run_batch(bf, max_steps=4000), (
                f"{backend}: churn round {r} did not drain")
            for k, p in zip(keys, pays):
                latest[int(k)] = p
            if r == rounds // 2:
                assert store.heap_gc(reason="gate-midload"), (
                    f"{backend}: mid-load GC skipped on a drained store")
        pressure_gcs = store.heap.gc_runs
        assert pressure_gcs >= 2, (
            f"{backend}: churn against a {cfg.heap_bytes}-byte log ran "
            f"only {pressure_gcs} GC(s) — the pressure path never engaged")
        stats = store.heap_gc(reason="gate-final")
        assert stats, f"{backend}: final GC skipped"
        util = stats["live_bytes"] / stats["used_bytes"]
        assert util >= UTIL_FLOOR, (
            f"{backend}: post-compaction utilization {util:.3f} < "
            f"{UTIL_FLOOR} — compaction is leaking dead extents")
        assert stats["used_bytes"] <= cfg.heap_bytes, backend

        # byte-exactness: the client path AND the raw device log agree
        # with the authoritative mirror for every surviving key
        skeys = np.asarray(sorted(latest), np.int64)
        res = store.multi_get(skeys)
        assert res.all_done()
        for j, k in enumerate(skeys):
            assert res.data[j] == latest[int(k)], (
                f"{backend}: key {int(k)} bytes diverged after GC")
        refs = np.asarray(res.value)[:, 0].astype(np.int32)
        rows, dlens = store.heap.device_gather(refs)
        for j, k in enumerate(skeys):
            got = rows[j, : int(dlens[j])].tobytes()
            assert got == latest[int(k)], (
                f"{backend}: device log diverged from mirror at key "
                f"{int(k)}")
        v = store.rt.check()
        assert v.ok, (f"{backend} checker FAIL: "
                      f"{[f.reason[:160] for f in v.failures[:2]]}")
        stale = lin.stale_read(store.rt.history_ops())
        assert stale == [], f"{backend}: stale reads {stale[:2]}"
        report[f"{backend}_soak"] = dict(
            churn_ops=rounds * per, keys_live=int(skeys.size),
            gc_runs=int(store.heap.gc_runs),
            reclaimed_bytes=int(store.heap.gc_reclaimed_bytes),
            post_gc_util=round(util, 4), util_floor=UTIL_FLOOR,
            checker_ok=True, stale_read=0)


def check_fleet_migration(report: dict) -> None:
    import numpy as np

    from hermes_tpu.config import FleetConfig
    from hermes_tpu.fleet import Fleet, verify_fleet
    from hermes_tpu.kvs import KVS
    from hermes_tpu.workload.ycsb import value_payload, value_sizes

    base = _cfg(n_keys=48, n_sessions=4, replay_slots=4,
                heap_bytes=1 << 14)
    fleet = Fleet(FleetConfig(groups=2, base=base,
                              ranges=((0, 32), (32, 64))), record=True)
    n = 40
    keys = np.arange(n, dtype=np.int64)
    lens = value_sizes(dict(n=n, max_bytes=base.max_value_bytes), SEED + 1)
    pays = [value_payload(SEED + 1, i, int(lens[i])) for i in range(n)]
    fb = fleet.submit_batch(np.full(n, KVS.PUT, np.int32), keys, pays)
    for _ in range(6000):
        if fb.all_done():
            break
        fleet.step()
    assert fb.all_done(), "fleet puts did not drain"
    summary = fleet.migrate(0, 8, 1)
    assert summary.get("heap_extents", 0) == 8, (
        f"migration moved {summary.get('heap_extents')} extents, wanted 8")
    res = fleet.multi_get(keys)
    for _ in range(6000):
        if res.all_done():
            break
        fleet.step()
    assert res.all_done()
    for i in range(n):
        assert res.data[i] == pays[i], (
            f"fleet key {i} bytes diverged across the migration")
    verdicts = fleet.check()
    assert verdicts["ok"], f"fleet checker FAIL {verdicts}"
    verify_fleet(fleet)
    report["fleet_migration"] = dict(
        keys=n, migrated_extents=int(summary["heap_extents"]),
        byte_exact=True, checker_ok=True)


def check_torn_snapshot(report: dict) -> None:
    import tempfile

    import numpy as np

    from hermes_tpu import snapshot
    from hermes_tpu.kvs import KVS
    from hermes_tpu.workload.ycsb import value_payload, value_sizes

    store = KVS(_cfg())
    n = 32
    lens = value_sizes(dict(n=n, max_bytes=256), SEED + 2)
    pays = [value_payload(SEED + 2, i, int(lens[i])) for i in range(n)]
    bf = store.submit_batch(np.full(n, KVS.PUT, np.int32),
                            np.arange(n, dtype=np.int64), pays)
    assert store.run_batch(bf)
    with tempfile.TemporaryDirectory(prefix="hermes_heap_gate_") as d:
        p = os.path.join(d, "heap.npz")
        snapshot.save(p, store)
        tgt = KVS(_cfg())
        snapshot.load(p, tgt)
        res = tgt.multi_get(np.arange(n, dtype=np.int64))
        assert res.all_done()
        for i in range(n):
            assert res.data[i] == pays[i], (
                f"key {i} bytes diverged across snapshot restore")
        torn = os.path.join(d, "torn.npz")
        with zipfile.ZipFile(p) as zin, zipfile.ZipFile(torn, "w") as zout:
            for name in zin.namelist():
                data = bytearray(zin.read(name))
                if name.startswith("kvs.heap.log"):
                    data[len(data) // 2] ^= 0xFF
                zout.writestr(name, bytes(data))
        try:
            snapshot.load(torn, KVS(_cfg()))
        except ValueError:
            pass
        else:
            raise AssertionError(
                "a bit-flipped heap log LOADED — the torn-snapshot "
                "checksum is not covering the value heap")
    report["torn_snapshot"] = dict(restore_byte_exact=True, torn_red=True)


def check_census_unchanged(report: dict) -> None:
    """The round census must not know the heap exists."""
    import bench
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from hermes_tpu import heap as heap_lib
    from hermes_tpu.obs import profile as prof

    cfg = bench._cfg("a")
    heap_cfg = dataclasses.replace(
        cfg, value_words=max(3, cfg.value_words), max_value_bytes=1024,
        heap_bytes=1 << 22)
    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    pairs = {
        "batched": (prof.op_census(cfg, "batched"),
                    prof.op_census(heap_cfg, "batched")),
        "sharded": (prof.op_census(cfg, "sharded", mesh),
                    prof.op_census(heap_cfg, "sharded", mesh)),
        "batched_mega": (
            prof.op_census(dataclasses.replace(cfg, mega_round=True),
                           "batched"),
            prof.op_census(dataclasses.replace(heap_cfg, mega_round=True),
                           "batched")),
        "sharded_mega": (
            prof.op_census(dataclasses.replace(cfg, mega_round=True),
                           "sharded", mesh),
            prof.op_census(dataclasses.replace(heap_cfg, mega_round=True),
                           "sharded", mesh)),
    }
    with open("OP_BUDGET.json") as f:
        budget = json.load(f)
    for engine, (word, heap) in pairs.items():
        assert word == heap, (
            f"{engine}: heap mode MOVED the round census — the protocol "
            f"is carrying value bytes (fixed-word {word} vs heap {heap})")
        assert heap["sparse_total"] <= budget[engine]["sparse_total"], (
            f"{engine}: sparse_total {heap['sparse_total']} over budget")
    gather = heap_lib.gather_census(heap_cfg, batch=1024)
    append = heap_lib.append_census(heap_cfg, chunk=4096)
    for name, cen in (("heap_path", gather), ("heap_append", append)):
        for k, ceiling in budget[name].items():
            assert cen[k] <= ceiling, (
                f"{name}.{k}: {cen[k]} exceeds the budget ceiling "
                f"{ceiling}")
    findings = heap_lib.analyze_gather(heap_cfg, batch=1024)
    assert findings == [], (
        f"extent gather analyzer findings: {[str(f) for f in findings[:3]]}")
    report["census_unchanged"] = dict(
        engines={e: p[0]["sparse_total"] for e, p in pairs.items()},
        heap_path_sparse=gather["sparse_total"],
        heap_append_sparse=append["sparse_total"],
        analyzer_findings=0)


def main() -> int:
    report: dict = {"gate": "heap"}
    try:
        check_census_unchanged(report)
        check_torn_snapshot(report)
        check_heap_soak(report)
        check_fleet_migration(report)
    except AssertionError as e:
        report["ok"] = False
        report["error"] = str(e)
        print(json.dumps(report, default=str))
        return 1
    report["ok"] = True
    out = os.path.join(os.path.dirname(__file__), "..", "HEAP_SOAK.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True, default=str)
        f.write("\n")
    print(json.dumps(report, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
