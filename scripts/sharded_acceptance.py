"""Sharded-backend acceptance artifact (round-4 verdict weak #6).

Runs the acceptance configs END-TO-END on the SHARDED engine
(`backend="sharded"` — the transport=tpu_ici program shape: one replica
per mesh device, INV/ACK/VAL on real collectives) over the 8-device
virtual CPU mesh, checker on, and writes ``ACCEPTANCE_SHARDED.json``.
This is the artifact the batched-only ACCEPTANCE_FULL.json could not
give: the wire path exercised through every scenario (stall detection,
remove/join state transfer, contention, RMW retries, the sparse-key
client KVS), not just through equality tests at small shapes.

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/sharded_acceptance.py [--scale 0.1]

Each config builds a mesh of exactly its n_replicas devices (3/5/7/8 of
the virtual 8).  Scale 0.1 keeps the CPU wall time in minutes; the shapes
still cover 100k keys and ~100 sessions/replica.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=0.1)
    ap.add_argument("--max-steps", type=int, default=20000)
    ap.add_argument("--configs", default="1,2,2r,3,3c,4,5,s")
    ap.add_argument("--check-keys", type=int, default=0,
                    help="checker key sample; 0 = every touched key")
    ap.add_argument("--out", default="ACCEPTANCE_SHARDED.json")
    args = ap.parse_args()

    import jax
    from jax.sharding import Mesh

    from hermes_tpu import acceptance

    devs = jax.devices()
    assert devs[0].platform == "cpu" and len(devs) >= 8, (
        "run under the 8-device virtual CPU mesh env (see module docstring)")

    toks = [x.strip() for x in args.configs.split(",")]
    results = {}
    for tok in toks:
        t0 = time.perf_counter()
        if tok == "s":
            n_rep = 3  # single source: passed to both the mesh and the cfg
            mesh = Mesh(np.array(devs[:n_rep]), ("replica",))
            counters, verdict = acceptance.run_sparse_variant(
                scale=args.scale, max_steps=args.max_steps,
                check_keys=args.check_keys or None,
                backend="sharded", mesh=mesh, n_replicas=n_rep,
                log=lambda s: print(f"  {s}", file=sys.stderr),
            )
        else:
            cfg_n = tok if tok in ("2r", "3c") else int(tok)
            n_rep = acceptance._cfg(cfg_n, args.scale).n_replicas
            mesh = Mesh(np.array(devs[:n_rep]), ("replica",))
            counters, verdict = acceptance.run_config(
                cfg_n, scale=args.scale, max_steps=args.max_steps,
                backend="sharded", mesh=mesh,
                check_keys=args.check_keys or None,
                log=lambda s: print(f"  {s}", file=sys.stderr),
            )
        wall = time.perf_counter() - t0
        entry = {"counters": counters, "wall_s": round(wall, 1),
                 "n_replicas": n_rep}
        entry.update(verdict.to_dict() if verdict else {
            "verdict_ok": None, "keys_checked": None,
            "failures": [], "undecided": [],
        })
        results[tok] = entry
        print(f"config {tok} (sharded, R={n_rep}): ok={entry['verdict_ok']} "
              f"drained={counters.get('drained')} wall={wall:.1f}s",
              file=sys.stderr)

    out = {
        "backend": "sharded",
        "scale": args.scale,
        "platform": devs[0].platform,
        "n_devices": len(devs),
        "results": results,
        "all_ok": all(r["verdict_ok"] and r["counters"].get("drained")
                      for r in results.values()),
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"acceptance_sharded_all_ok": out["all_ok"]}))


if __name__ == "__main__":
    main()
