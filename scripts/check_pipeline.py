"""Round-8 serving-pipeline gate (CI): the async harvest ring and the
donated state tree must be BEHAVIOR-INVISIBLE.

Three assertions, CPU-smoke sized (joins scripts/check_op_census.py,
check_obs_overhead.py and check_analysis.py in the verify flow):

  1. sync <-> pipelined state identity: the same stream through
     FastRuntime at pipeline_depth 1 vs >= 2 yields byte-identical state
     trees and Meta counters on BOTH engines, and a checker-gated
     pipelined KVS run (depth 2) passes linearizability;
  2. donation is loud, and the DONATED round program passes the static
     analyzer (hermes_tpu.analysis) with no findings beyond
     ANALYSIS_BASELINE.json — which must stay EMPTY (the analyzer's
     scatter pass includes the donation-aliasability check, so a state
     output XLA cannot alias back onto its donated input surfaces here);
  3. zero steady-state per-round control uploads: the ctl_upload trace
     event fires once at first dispatch and then only on membership/fault
     transitions.

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/check_pipeline.py

Prints one JSON line; exit non-zero on any violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")


def check_state_identity(report: dict) -> None:
    import dataclasses

    import jax
    import numpy as np

    from hermes_tpu.config import HermesConfig, WorkloadConfig
    from hermes_tpu.runtime import FastRuntime

    def run(depth, backend, mesh):
        cfg = HermesConfig(
            n_replicas=8 if backend == "sharded" else 3,
            n_keys=64, n_sessions=4, replay_slots=2, ops_per_session=8,
            pipeline_depth=depth,
            workload=WorkloadConfig(read_frac=0.5, rmw_frac=0.3, seed=37),
        )
        rt = FastRuntime(cfg, backend=backend, mesh=mesh)
        assert rt.drain(400), f"{backend} depth={depth} did not drain"
        return rt

    for backend in ("batched", "sharded"):
        mesh = None
        if backend == "sharded":
            from jax.sharding import Mesh

            mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
        a, b = run(1, backend, mesh), run(3, backend, mesh)
        la = jax.tree.leaves(jax.device_get(a.fs))
        lb = jax.tree.leaves(jax.device_get(b.fs))
        for x, y in zip(la, lb):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        report[f"{backend}_state_identical"] = True


def check_kvs_pipelined(report: dict) -> None:
    from hermes_tpu.config import HermesConfig
    from hermes_tpu.kvs import KVS

    cfg = HermesConfig(n_replicas=3, n_keys=128, value_words=6, n_sessions=8,
                       replay_slots=2, ops_per_session=1, pipeline_depth=2)
    kvs = KVS(cfg, record=True)
    futs = [kvs.put(i % 3, (i // 3) % 8, i % 11, [i, i + 1, 3, 4])
            for i in range(24)]
    futs += [kvs.rmw(i % 3, (i + 4) % 8, i % 11, [90 + i, 0, 0, 0])
             for i in range(6)]
    assert kvs.run_until(futs, 300), "pipelined KVS did not resolve"
    v = kvs.rt.check()
    assert v.ok, f"pipelined KVS checker FAIL: {v.failures[:2]}"
    report["kvs_depth2_checked"] = True


def check_donation_and_analysis(report: dict) -> None:
    import jax
    import numpy as np

    from hermes_tpu import analysis as ana
    from hermes_tpu.config import HermesConfig
    from hermes_tpu.runtime import FastRuntime

    rt = FastRuntime(HermesConfig(n_replicas=3, n_keys=64, n_sessions=4,
                                  replay_slots=2, ops_per_session=4))
    old = rt.fs
    rt.step_once()
    try:
        np.asarray(jax.device_get(old.table.vpts))
        raise AssertionError("superseded donated state was readable")
    except RuntimeError:
        report["donation_red"] = True

    with open(os.path.join(os.path.dirname(__file__), "..",
                           "ANALYSIS_BASELINE.json")) as f:
        base = json.load(f)
    grandfathered = base.get("grandfathered", {})
    assert not grandfathered, (
        "ANALYSIS_BASELINE.json must stay empty (round-8 contract); found "
        f"{len(grandfathered)} grandfathered finding(s)")
    gating = []
    for rep in ana.analyze_config(HermesConfig(), engines=("batched",),
                                  variants="as-is"):
        gating += [f for f in rep["findings"] if f.severity in ana.GATING]
    assert not gating, f"analyzer findings on the donated program: {gating[:3]}"
    report["analysis_clean"] = True


def check_ctl_uploads(report: dict) -> None:
    from hermes_tpu.config import HermesConfig
    from hermes_tpu.obs import Observability
    from hermes_tpu.runtime import FastRuntime

    rt = FastRuntime(HermesConfig(n_replicas=3, n_keys=64, n_sessions=4,
                                  replay_slots=2, ops_per_session=16))
    obs = rt.attach_obs(Observability())
    rt.run(10)
    rt.freeze(1)
    rt.run(5)
    ups = sum(1 for r in obs.records
              if r.get("kind") == "event" and r.get("name") == "ctl_upload")
    assert ups == 2, f"expected 2 ctl uploads (init + freeze), saw {ups}"
    report["ctl_uploads_steady_state_zero"] = True


def main() -> int:
    report: dict = {"gate": "pipeline"}
    try:
        check_state_identity(report)
        check_kvs_pipelined(report)
        check_donation_and_analysis(report)
        check_ctl_uploads(report)
    except AssertionError as e:
        report["ok"] = False
        report["error"] = str(e)
        print(json.dumps(report))
        return 1
    report["ok"] = True
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
