"""Round-chain op-census gate (CI): the fast round's sparse/collective op
counts must stay within the checked-in budget, and the committed
SHARDED_CENSUS.json census section must match what the code actually
lowers to.

Why a gate: the engine's measured cost model (ARCHITECTURE.md "Sparse-op
COUNT dominates") prices a protocol round as (#sparse ops) x ~1.3-2.4 ms
nearly independent of operand size, so ONE gather/scatter/sort quietly
re-added by a refactor costs ~6% of the headline writes/sec — and nothing
else in CI would notice.  Same measure-then-gate pattern as
scripts/check_obs_overhead.py.

The census is computed by abstract lowering (hermes_tpu.obs.profile.
op_census) at the exact bench shape — backend-independent, so this runs on
the CPU env; the TPU-only timing cells of SHARDED_CENSUS.json
(tpu_r1_delta) are never touched here.

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/check_op_census.py [--update]

``--update`` rewrites the census section (and the census-derived
projection) of SHARDED_CENSUS.json in place after an INTENTIONAL op-count
change — the diff then shows up in review instead of drifting silently.
Exits non-zero on any budget breach or un-updated drift.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh  # noqa: E402

from hermes_tpu.obs import profile as prof  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--budget", default="OP_BUDGET.json")
    ap.add_argument("--census", default="SHARDED_CENSUS.json")
    ap.add_argument("--update", action="store_true",
                    help="rewrite the census (+ derived projection) section "
                    "of the census artifact instead of failing on drift")
    args = ap.parse_args()

    import dataclasses

    import bench

    cfg = bench._cfg("a")
    mega = dataclasses.replace(cfg, mega_round=True)
    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    print(f"censusing bench shape (S={cfg.n_sessions}, C={cfg.lane_budget}, "
          f"K={cfg.n_keys}, fused_sort={cfg.use_fused_sort}) + mega path...",
          file=sys.stderr)
    from hermes_tpu.core import readpath

    measured = {
        "batched": prof.op_census(cfg, "batched"),
        "sharded": prof.op_census(cfg, "sharded", mesh),
        # round-15: the mega path is budgeted separately (batched must
        # hold the 4-sparse-op floor; the pallas_* ceilings police the
        # kernel interiors the plain census cannot see)
        "batched_mega": prof.op_census(mega, "batched"),
        "sharded_mega": prof.op_census(mega, "sharded", mesh),
        # round-16: the local-read fast path is a SEPARATE dispatch —
        # the round sections above not moving IS the zero-round-impact
        # proof; these police the read programs' own op diet (one
        # gather for a whole multi-get, zero sparse ops for a scan)
        "read_path": readpath.read_census(cfg, "batched"),
        "read_scan": readpath.scan_census(cfg, "batched"),
    }
    # round-17: the value heap's own dispatches (hermes_tpu/heap) — the
    # extent gather must answer a whole ref batch with ONE sparse op and
    # the log append must stay dense; the round sections above not
    # moving is the proof the protocol still carries only the packed
    # HEAP_REF word (the extent lands before the INV issues)
    from hermes_tpu import heap as heap_lib

    hcfg = dataclasses.replace(cfg, value_words=max(3, cfg.value_words),
                               max_value_bytes=1024, heap_bytes=1 << 22)
    measured["heap_path"] = heap_lib.gather_census(hcfg, batch=1024)
    measured["heap_append"] = heap_lib.append_census(hcfg, chunk=4096)

    with open(args.budget) as f:
        budget = {k: v for k, v in json.load(f).items()
                  if not k.startswith("_")}
    failures = prof.check_budget(measured, budget)

    # round-18: per-op tracing is host-side only — trace ids ride the
    # Future, never the queue tuples or the device stream — so the lowered
    # round program must be op-for-op identical with the sampler armed.
    # Census equality at trace_sample=64 is that proof.
    traced_cfg = dataclasses.replace(cfg, trace_sample=64)
    traced_mega = dataclasses.replace(mega, trace_sample=64)
    traced_census_identical = True
    for engine, tcfg, backend, m in (
            ("batched", traced_cfg, "batched", None),
            ("sharded", traced_cfg, "sharded", mesh),
            ("batched_mega", traced_mega, "batched", None),
            ("sharded_mega", traced_mega, "sharded", mesh)):
        tc = prof.op_census(tcfg, backend, m) if m is not None else \
            prof.op_census(tcfg, backend)
        if tc != measured[engine]:
            traced_census_identical = False
            diff = {k: (tc.get(k), measured[engine].get(k))
                    for k in set(tc) | set(measured[engine])
                    if tc.get(k) != measured[engine].get(k)}
            failures.append(f"trace_sample=64 changed the {engine} round "
                            f"census: {diff} (traced vs untraced)")

    # drift check: the committed artifact's census must equal the lowered
    # program's (count keys only; the artifact may carry more context)
    drift = []
    try:
        with open(args.census) as f:
            artifact = json.load(f)
        recorded = artifact.get("census", {})
    except FileNotFoundError:
        artifact, recorded = None, {}
        drift.append(f"{args.census} missing")
    for engine, cen in measured.items():
        rec = recorded.get(engine, {})
        for k, v in cen.items():
            if rec.get(k) != v:
                drift.append(f"{engine}.{k}: artifact has {rec.get(k)}, "
                             f"code lowers to {v}")

    if drift and args.update and artifact is not None:
        from sharded_census import mega_projection, projection

        artifact["census"] = measured
        artifact["bench_shape"] = prof.census_shape(cfg)
        artifact["v5e8_projection"] = projection(measured["batched"],
                                                 measured["sharded"])
        artifact["mega_projection"] = mega_projection(
            measured["batched"], measured["batched_mega"])
        with open(args.census, "w") as f:
            json.dump(artifact, f, indent=1)
        print(f"updated {args.census} census section", file=sys.stderr)
        drift = []

    out = dict(ok=not failures and not drift,
               budget=budget, census=measured,
               budget_failures=failures, census_drift=drift)
    print(json.dumps(dict(ok=out["ok"],
                          sparse_batched=measured["batched"]["sparse_total"],
                          sparse_sharded=measured["sharded"]["sparse_total"],
                          collectives_sharded=measured["sharded"][
                              "collective_total"],
                          sparse_batched_mega=measured["batched_mega"][
                              "sparse_total"],
                          sparse_sharded_mega=measured["sharded_mega"][
                              "sparse_total"],
                          mega_serial_iter_bound=measured["batched_mega"][
                              "pallas_serial_iter_bound"],
                          sparse_read_path=measured["read_path"][
                              "sparse_total"],
                          sparse_read_scan=measured["read_scan"][
                              "sparse_total"],
                          sparse_heap_path=measured["heap_path"][
                              "sparse_total"],
                          sparse_heap_append=measured["heap_append"][
                              "sparse_total"],
                          traced_census_identical=traced_census_identical,
                          budget_failures=failures, census_drift=drift)))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
