"""Drive the client KVS API (hermes_tpu/kvs.py) at scale — the L5 session
API at engine-relevant throughput (round-3 verdict item 5): >=100k checked
client ops/s on the CPU mesh through the batched public path
(KVS.submit_batch, array-in futures-out; numpy-vectorized slot fill /
completion match / result store), recorded with the columnar recorder +
native witness checker.

Usage (CPU, scrubbed env)::

    env PYTHONPATH=/root/repo PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python scripts/kvs_scale.py --ops 100000 [--sparse]

Prints one JSON line: ops driven, completion count, enqueue / drive wall
seconds, client ops/s (steady-state: a warmup batch pays XLA compilation
before the timed drive), protocol rounds used, checker verdict.
"""

import argparse
import json
import sys
import time

sys.path.insert(0, ".")

import numpy as np


def run(ops: int = 100_000, replicas: int = 3, sessions: int = 1024,
        keys: int = 4096, sparse: bool = False, check: bool = True,
        warmup: bool = True, seed: int = 0) -> dict:
    from hermes_tpu.config import HermesConfig, WorkloadConfig
    from hermes_tpu.kvs import KVS, drive_mix

    cfg = HermesConfig(
        n_replicas=replicas, n_keys=keys, n_sessions=sessions,
        value_words=6, replay_slots=min(64, keys),
        workload=WorkloadConfig(seed=seed),
    )
    # columnar recorder + native witness when a compiler exists: the Python
    # per-op recorder would dominate the drive wall at this scale
    from hermes_tpu.checker.fast import default_record

    kvs = KVS(cfg, record=default_record(check), sparse_keys=sparse)

    def xform(k64: np.ndarray) -> np.ndarray:
        """Sparse client-key mapping: odd-constant affine map mod 2^64 is a
        bijection, so distinct dense keys stay distinct.  The reserved
        all-ones bucket sentinel (keyindex._EMPTY), if it appears, is
        remapped to the image of `keys` itself — outside the image of
        [0, keys), so injectivity is preserved (the round-3 advisor flagged
        the previous low-bit mask as non-injective)."""
        golden = np.uint64(0x9E3779B97F4A7C15)
        with np.errstate(over="ignore"):
            out = k64 * golden + np.uint64(1)
            spare = np.uint64(keys) * golden + np.uint64(1)
        out[out == np.uint64(0xFFFFFFFFFFFFFFFF)] = spare
        return out

    if warmup:
        # compile the round program before the timed drive: first-dispatch
        # XLA compilation (~seconds) is a session cost, not a per-op cost.
        # Warmup keys come from the run's own key universe so sparse mode
        # claims no extra dense slots.
        wk = np.arange(min(64, keys), dtype=np.uint64)
        if sparse:
            wk = xform(wk)
        wb = kvs.submit_batch(np.full(wk.shape[0], KVS.PUT, np.int32),
                              wk, np.ones((wk.shape[0], 1), np.int32))
        if not kvs.run_batch(wb, 200):
            raise RuntimeError(
                "warmup batch did not drain; the timed drive would include "
                "compilation and misreport steady-state ops/s")
    rng = np.random.default_rng(seed)
    is_get = rng.random(ops) < 0.5  # YCSB-A shaped 50/50 client mix
    op_keys = rng.integers(0, keys, ops).astype(np.uint64)
    if sparse:
        # arbitrary 64-bit client keys through the hash index
        op_keys = xform(op_keys)

    bf, all_done, enqueue_s, drive_s = drive_mix(
        kvs, op_keys, is_get, lambda i: [i & 0x7FFF, i >> 15])

    verdict = None
    check_s = None
    if check:
        t0 = time.perf_counter()
        verdict = bool(kvs.rt.check().ok)
        check_s = round(time.perf_counter() - t0, 3)

    completed = bf.done_count()
    return {
        "ops": ops,
        "completed": completed,
        "all_done": bool(all_done),
        "replicas": replicas,
        "sessions": sessions,
        "sparse_keys": sparse,
        "enqueue_s": round(enqueue_s, 3),
        "drive_s": round(drive_s, 3),
        "client_ops_per_s": round(completed / drive_s, 1),
        "rounds": kvs.rt.step_idx,
        "checked_ok": verdict,
        "check_s": check_s,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=100_000)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--sessions", type=int, default=1024)
    ap.add_argument("--keys", type=int, default=4096)
    ap.add_argument("--sparse", action="store_true")
    ap.add_argument("--no-check", action="store_true")
    args = ap.parse_args()
    rec = run(ops=args.ops, replicas=args.replicas, sessions=args.sessions,
              keys=args.keys, sparse=args.sparse, check=not args.no_check)
    print(json.dumps(rec))
    if not rec["all_done"] or rec["checked_ok"] is False:
        sys.exit(1)


if __name__ == "__main__":
    main()
