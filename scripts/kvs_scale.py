"""Drive the client KVS API (hermes_tpu/kvs.py) at moderate scale — the
round-2 verdict item 7 demonstration that the L5 session API is known-good
beyond toy sizes: >=10k client ops through get/put futures over
(replica, session) slots, wall-clock reported, and (by default) the run
recorded + linearizability-checked.

Usage (CPU, scrubbed env)::

    env PYTHONPATH=/root/repo PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python scripts/kvs_scale.py --ops 20000

Prints one JSON line: ops driven, completion count, enqueue / drive wall
seconds, client ops/s, protocol rounds used, checker verdict.
"""

import argparse
import json
import sys
import time

import numpy as np


def run(ops: int = 20000, replicas: int = 3, sessions: int = 1024,
        keys: int = 4096, sparse: bool = False, check: bool = True,
        seed: int = 0) -> dict:
    from hermes_tpu.config import HermesConfig, WorkloadConfig
    from hermes_tpu.kvs import KVS, drive_mix

    cfg = HermesConfig(
        n_replicas=replicas, n_keys=keys, n_sessions=sessions,
        value_words=6, replay_slots=min(64, keys),
        workload=WorkloadConfig(seed=seed),
    )
    kvs = KVS(cfg, record=check, sparse_keys=sparse)
    rng = np.random.default_rng(seed)
    is_get = rng.random(ops) < 0.5  # YCSB-A shaped 50/50 client mix
    op_keys = rng.integers(0, keys, ops).astype(np.uint64)
    if sparse:
        # arbitrary 64-bit client keys through the hash index
        with np.errstate(over="ignore"):
            op_keys = (op_keys * np.uint64(0x9E3779B97F4A7C15)
                       + np.uint64(1)) & np.uint64((1 << 64) - 2)

    futs, all_done, enqueue_s, drive_s = drive_mix(
        kvs, op_keys, is_get, lambda i: [i & 0x7FFF, i >> 15])

    verdict = None
    check_s = None
    if check:
        t0 = time.perf_counter()
        verdict = bool(kvs.rt.check().ok)
        check_s = round(time.perf_counter() - t0, 3)

    completed = sum(f.done() for f in futs)
    return {
        "ops": ops,
        "completed": completed,
        "all_done": bool(all_done),
        "replicas": replicas,
        "sessions": sessions,
        "sparse_keys": sparse,
        "enqueue_s": round(enqueue_s, 3),
        "drive_s": round(drive_s, 3),
        "client_ops_per_s": round(completed / drive_s, 1),
        "rounds": kvs.rt.step_idx,
        "checked_ok": verdict,
        "check_s": check_s,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=20000)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--sessions", type=int, default=1024)
    ap.add_argument("--keys", type=int, default=4096)
    ap.add_argument("--sparse", action="store_true")
    ap.add_argument("--no-check", action="store_true")
    args = ap.parse_args()
    rec = run(ops=args.ops, replicas=args.replicas, sessions=args.sessions,
              keys=args.keys, sparse=args.sparse, check=not args.no_check)
    print(json.dumps(rec))
    if not rec["all_done"] or rec["checked_ok"] is False:
        sys.exit(1)


if __name__ == "__main__":
    main()
