"""Round-22 durability gate (CI): kill -9 the store mid-soak, recover,
lose NOTHING a client was told was committed.

Three legs, CPU-smoke sized (joins the eleven existing gates in
scripts/run_gates.py as the twelfth):

  1/2. kill_batched / kill_sharded — spawn scripts/_durability_soak.py
     (put waves at 2x in-flight capacity, ``wal_sync='commit'``); the
     child's own chaos schedule fires a ``powercut`` verb mid-wave whose
     carrier SIGKILLs the whole process — in-flight batch, dirty WAL
     window, no cleanup.  The parent then recovers IN-PROCESS via
     chaos.recovery.recover_store and asserts:
       * ``committed_write_lost(committed, ops) == []`` — every write a
         client saw resolve is a definite committed write in the
         replayed log (the zero-loss contract, checker-green);
       * the recovered store SERVES the per-key newest logged value;
       * recovery wall time stays under RECOVERY_BOUND_S;
       * the recovered store still accepts and commits new writes.
  3. wal_overhead — the same drive loop with the WAL on (commit) vs off,
     writes/s both ways, reported as a measured cell (record-only: the
     fsync tax is the product, not a regression).

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/check_durability.py

Prints one JSON line (also written to DURABILITY_SOAK.json); exit
non-zero on any violation.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, ".")
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import _durability_soak as soak

KILL_WAVE = 4
RECOVERY_BOUND_S = 90.0
CHILD_TIMEOUT_S = 420


def _read_commits(path):
    """The child's witness set; a line torn by the SIGKILL only shrinks
    it (the child flushes per wave, so only the last line can tear)."""
    committed = []
    with open(path) as f:
        for ln in f:
            try:
                committed.append(json.loads(ln))
            except json.JSONDecodeError:
                break
    return committed


def _log_ops(records):
    """Every logged write as a definite committed checker op: uid rides
    in value words 0-1, the (ver, fc) witness in its own columns."""
    from hermes_tpu.checker.history import Op

    ops = []
    for rec in records:
        for i in range(int(rec["key"].shape[0])):
            step = int(rec["step"][i])
            ops.append(Op(
                "w", int(rec["key"][i]), 2 * step, 2 * step + 1,
                wuid=(int(rec["wv"][i, 0]), int(rec["wv"][i, 1])),
                ts=(int(rec["ver"][i]), int(rec["fc"][i]))))
    return ops


def check_kill(report: dict, backend: str) -> None:
    from hermes_tpu.chaos.recovery import recover_store
    from hermes_tpu.checker.linearizability import committed_write_lost
    from hermes_tpu.wal import replay as wal_replay

    d = tempfile.mkdtemp(prefix=f"durability_{backend}_")
    wal_dir = os.path.join(d, "wal")
    commits = os.path.join(d, "commits.jsonl")
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "_durability_soak.py")
    p = subprocess.run(
        [sys.executable, script, wal_dir, backend, commits, str(KILL_WAVE)],
        timeout=CHILD_TIMEOUT_S, capture_output=True, text=True)
    assert p.returncode == -signal.SIGKILL, (
        f"{backend}: soak child exited {p.returncode}, want "
        f"-SIGKILL from its own powercut carrier\n{p.stderr[-2000:]}")
    committed = _read_commits(commits)
    assert committed, f"{backend}: child logged no committed writes"

    # parse the dead store's log BEFORE recovery consumes it: these
    # records are the history the checker cross-examines
    scan = wal_replay.read_records(wal_dir)
    ops = _log_ops(scan["records"])

    import jax
    import numpy as np

    mesh = None
    if backend == "sharded":
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:soak.N_REPLICAS]), ("replica",))
    t0 = time.perf_counter()
    kvs, rsum = recover_store(soak.soak_cfg(wal_dir), backend=backend,
                              mesh=mesh)
    recovery_s = time.perf_counter() - t0

    lost = committed_write_lost([tuple(c["uid"]) for c in committed], ops)
    assert lost == [], (
        f"{backend}: {len(lost)} committed write(s) LOST across the "
        f"kill -9 (first: {lost[:5]}) — the durability contract is void")
    assert recovery_s < RECOVERY_BOUND_S, (
        f"{backend}: recovery took {recovery_s:.1f}s "
        f"(bound {RECOVERY_BOUND_S}s)")

    # functional: the recovered store must SERVE each key's newest
    # logged value, not merely hold rows
    newest = {}
    for rec in scan["records"]:
        for i in range(int(rec["key"].shape[0])):
            k = int(rec["key"][i])
            ts = (int(rec["ver"][i]), int(rec["fc"][i]))
            if k not in newest or ts > newest[k][0]:
                newest[k] = (ts, rec["wv"][i, 2:].tolist())
    served = 0
    for k, (_ts, want) in sorted(newest.items())[:16]:
        fut = kvs.get(0, 0, k)
        assert kvs.run_until([fut]), f"{backend}: get({k}) never resolved"
        c = fut.result()
        assert c.found and c.value == want, (
            f"{backend}: recovered store serves {c.value} for key {k}, "
            f"log says {want}")
        served += 1

    # and it must still be a store: fresh writes commit durably
    n_new = soak.run_waves(kvs, 1, rng_seed=soak.SEED + 1)
    assert n_new > 0, f"{backend}: no post-recovery write committed"
    kvs.wal.close()
    report[f"kill_{backend}"] = dict(
        committed_witnessed=len(committed), log_records=len(ops),
        committed_write_lost=[], torn_tail=bool(scan["torn_tail"]),
        applied=rsum["applied"], skipped=rsum["skipped"],
        recovery_s=round(recovery_s, 3), keys_served=served,
        post_recovery_commits=n_new)


def check_wal_overhead(report: dict) -> None:
    """Measured cell: writes/s with the WAL on (group-commit fsync per
    resolved round) vs off.  Record-only — the tax is the product."""
    d = tempfile.mkdtemp(prefix="durability_overhead_")
    cells = {}
    for label, wal_dir in (("wal_off", None),
                           ("wal_on", os.path.join(d, "wal"))):
        kvs = soak.build_kvs(wal_dir, "batched")
        soak.run_waves(kvs, 1)  # warm the jit caches off the clock
        t0 = time.perf_counter()
        n = soak.run_waves(kvs, 4, rng_seed=soak.SEED + 2)
        dt = time.perf_counter() - t0
        cells[label] = dict(writes=n, seconds=round(dt, 3),
                            writes_per_s=round(n / dt, 1))
        if kvs.wal is not None:
            cells[label]["fsyncs"] = kvs.wal.stats()["fsyncs"]
            kvs.wal.close()
    on, off = cells["wal_on"]["writes_per_s"], cells["wal_off"]["writes_per_s"]
    cells["on_vs_off"] = round(on / off, 3) if off else None
    report["wal_overhead"] = cells


def main() -> int:
    report: dict = {"gate": "durability"}
    try:
        check_kill(report, "batched")
        check_kill(report, "sharded")
        check_wal_overhead(report)
    except AssertionError as e:
        report["ok"] = False
        report["error"] = str(e)
        print(json.dumps(report))
        return 1
    report["ok"] = True
    out = os.path.join(os.path.dirname(__file__), "..",
                       "DURABILITY_SOAK.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
