"""Host concurrency gate (CI gate ELEVEN): the threaded serving /
transport / obs tier must prove clean against the declarative guard
registry (hermes_tpu/concurrency.py) — statically AND under the dynamic
lock-order sanitizer — with a committed-EMPTY baseline.

Four legs, each timed into the JSON line (run_gates.py hoists the
per-leg seconds into GATES_SUMMARY.json):

  * ``static``      — ``hostlint.lint_package()`` over the whole package
    vs HOSTLINT_BASELINE.json (``--update`` rewrites; the shipped table
    is EMPTY — violations get fixed, not grandfathered).
  * ``red_static``  — the lint must still be able to FAIL: an injected
    unguarded ``_conns`` write on TcpRpcServer and an injected
    nested-``with`` A->B / B->A pair must both flip findings.  A lint
    that stopped firing is a broken gate, not a clean codebase.
  * ``red_dynamic`` — two ObsLocks acquired in opposite orders by two
    (sequential — no real deadlock risk) threads must produce a
    lock-order-cycle finding carrying BOTH acquisition stacks.
  * ``soak``        — a short real columnar-serving drive (TCP server +
    client, the test_serving_columnar.py shape) with HERMES_LOCKLINT=1,
    i.e. every make_lock-minted lock is an ObsLock: zero cycles, every
    per-lock hold-time p99 under ``--max-hold-p99-us``, and the
    ``lock_*`` series actually present in the attached registry (the
    sanitizer demonstrably deployed, not silently off).  The graph is
    reset AFTER a jit-warmup batch so compile-time holds don't pollute
    the percentiles.

    env JAX_PLATFORMS=cpu python scripts/check_hostlint.py \
        [--update] [--static-only] [--out FINDINGS_JSONL]

Exit non-zero on any new static finding, any missing red flip, or a
soak violation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the soak needs the switch ON before any serving lock is minted
os.environ["HERMES_LOCKLINT"] = "1"


# an unguarded write of a registry-guarded attribute on the real class
# name/module: the static pass MUST flag this or the gate is vacuous
RED_GUARDED_SRC = '''
class TcpRpcServer:
    def _accept_loop(self):
        self._conns.append(object())
'''

# a nested-with order inversion: f takes a->b, g takes b->a
RED_ORDER_SRC = '''
def f():
    with a_lock:
        with b_lock:
            pass


def g():
    with b_lock:
        with a_lock:
            pass
'''


def leg_static(args, ana, hostlint):
    report = hostlint.lint_package()
    measured = ana.key_counts(report["findings"])
    baseline = ana.load_baseline(args.baseline)
    new, stale = ana.diff_baseline(measured, baseline)

    if (new or stale) and args.update:
        doc = {
            "_doc": "Grandfathered host-concurrency findings "
                    "(scripts/check_hostlint.py).  Keys are line-number-"
                    "free; rewrite with --update after an INTENTIONAL "
                    "change and commit the diff.  This table ships EMPTY "
                    "— a violation gets a lock, an audited() declaration "
                    "with a justification, or a fix, never a baseline "
                    "entry.",
            "grandfathered": {
                k: {"count": c,
                    "note": next((f.message for f in report["findings"]
                                  if f.key == k), "")}
                for k, c in sorted(measured.items())
            },
        }
        with open(args.baseline, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"updated {args.baseline} ({len(measured)} grandfathered)",
              file=sys.stderr)
        new, stale = {}, {}

    if args.out:
        ana.export_findings(args.out, [report], extra={"config": "host"})

    for k in sorted(new):
        print(f"NEW host finding: {k} (+{new[k]})", file=sys.stderr)
    for k in sorted(stale):
        print(f"stale baseline entry (no longer produced; --update "
              f"prunes): {k}", file=sys.stderr)
    by_sev = {s: sum(f.count for f in report["findings"]
                     if f.severity == s)
              for s in (ana.ERROR, ana.WARN, ana.INFO)}
    return dict(ok=not new, proved=report["proved"],
                errors=by_sev[ana.ERROR], warnings=by_sev[ana.WARN],
                infos=by_sev[ana.INFO], gating_sites=len(measured),
                new_findings=sorted(new), stale_baseline=sorted(stale))


def leg_red_static(ana, hostlint):
    guarded = hostlint.lint_source(
        RED_GUARDED_SRC, module="hermes_tpu.serving.rpc",
        relfile="<red:guarded>")
    guarded_hit = any(f.code == "guarded-attr-unlocked"
                      and f.severity == ana.ERROR and f.op == "_conns"
                      for f in guarded)
    order = hostlint.lint_source(
        RED_ORDER_SRC, module="redmod", relfile="<red:order>")
    order_hit = any(f.code == "lock-order-cycle"
                    and f.severity == ana.ERROR for f in order)
    if not guarded_hit:
        print("RED FAILURE: injected unguarded TcpRpcServer._conns "
              "write was NOT flagged — the static pass lost its teeth",
              file=sys.stderr)
    if not order_hit:
        print("RED FAILURE: injected a->b / b->a nested-with inversion "
              "produced no static lock-order-cycle", file=sys.stderr)
    return dict(ok=guarded_hit and order_hit,
                guarded_flip=guarded_hit, order_flip=order_hit)


def leg_red_dynamic(lockgraph):
    g = lockgraph.LockGraph()
    a = lockgraph.ObsLock("red.A", g)
    b = lockgraph.ObsLock("red.B", g)

    def fwd():
        with a:
            with b:
                pass

    def rev():
        with b:
            with a:
                pass

    # sequential threads: the inversion is recorded without ever racing
    for fn in (fwd, rev):
        t = threading.Thread(target=fn)
        t.start()
        t.join()
    cycles = g.cycles()
    findings = g.findings()
    evidence_ok = all("held at" in f.message
                      and "acquired at" in f.message for f in findings)
    ok = (len(cycles) == 1 and sorted(cycles[0]) == ["red.A", "red.B"]
          and len(findings) == 1 and evidence_ok)
    if not ok:
        print(f"RED FAILURE: opposite-order ObsLock acquisition yielded "
              f"cycles={cycles}, {len(findings)} finding(s), "
              f"evidence_ok={evidence_ok}", file=sys.stderr)
    return dict(ok=ok, cycles=cycles, n_findings=len(findings))


def leg_soak(args, lockgraph):
    import numpy as np

    from hermes_tpu.config import HermesConfig, WorkloadConfig
    from hermes_tpu.kvs import KVS
    from hermes_tpu.obs.metrics import MetricsRegistry
    from hermes_tpu.serving import (ColumnarClient, ColumnarFrontend,
                                    ColumnarTcpServer, ServingConfig,
                                    wire)

    cfg = HermesConfig(
        n_replicas=3, n_keys=64, n_sessions=4, replay_slots=6,
        ops_per_session=96, value_words=6, pipeline_depth=2,
        workload=WorkloadConfig(read_frac=0.5, seed=7))
    scfg = ServingConfig(tenant_rate_per_s=1e6, tenant_burst=1e4,
                         tenant_quota=16, queue_cap=64, round_us=1000)

    fe = ColumnarFrontend(KVS(cfg), scfg)

    def batch(kinds, keys, rid0, value=None):
        k = len(keys)
        return wire.ReqBatch(
            kind=np.asarray(kinds, np.uint8),
            req_id=np.arange(rid0, rid0 + k, dtype=np.uint32),
            tenant=np.zeros(k, np.uint16),
            trace=np.zeros(k, np.uint16),
            deadline_us=np.zeros(k, np.uint32),
            key=np.asarray(keys, np.int64),
            value=(np.asarray(value, np.int32) if value is not None
                   else np.zeros((k, fe.u), np.int32)))
    server = ColumnarTcpServer(fe)
    graph = None
    try:
        cl = ColumnarClient(server.addr, fe.u)
        val = np.arange(4 * fe.u, dtype=np.int32).reshape(4, fe.u)
        # warmup: jit-compiles the store round with compile-time lock
        # holds landing in the ABOUT-TO-BE-DISCARDED graph
        for _ in range(args.warmup_batches):
            cl.call_batch(batch([wire.K_PUT] * 4, [1, 2, 3, 4],
                                int(cl.next_ids(4)[0]), val))
        graph = lockgraph.reset_global()
        reg = MetricsRegistry()
        graph.attach_registry(reg)
        for i in range(args.soak_batches):
            keys = [(i * 4 + j) % cfg.n_keys for j in range(4)]
            rid0 = int(cl.next_ids(4)[0])
            if i % 2 == 0:
                rsps = cl.call_batch(
                    batch([wire.K_PUT] * 4, keys, rid0, val))
            else:
                rsps = cl.call_batch(batch([wire.K_GET] * 4, keys, rid0))
            if len(rsps) != 4:
                raise RuntimeError(
                    f"soak batch {i}: {len(rsps)}/4 responses")
        cl.close()
    finally:
        server.close()
    if server.pump_error is not None:
        raise server.pump_error

    rep = graph.report()
    cycles = rep["cycles"]
    lock_series = [n for n in reg.names()
                   if n.startswith(lockgraph.LOCK_METRIC_PREFIX)]
    hold_p99 = {n: st.get("hold_p99_us")
                for n, st in rep["locks"].items()}
    over = {n: p for n, p in hold_p99.items()
            if p is not None and p > args.max_hold_p99_us}
    ok = (not cycles and not over and bool(rep["locks"])
          and bool(lock_series))
    if cycles:
        for f in graph.findings():
            print(f"SOAK CYCLE: {f.message}", file=sys.stderr)
    if over:
        print(f"SOAK hold-time p99 over {args.max_hold_p99_us}us: "
              f"{over}", file=sys.stderr)
    if not rep["locks"] or not lock_series:
        print("SOAK FAILURE: no instrumented locks / no lock_* series "
              "recorded — HERMES_LOCKLINT plumbing is broken",
              file=sys.stderr)
    return dict(ok=ok, cycles=len(cycles), locks=rep["locks"],
                n_edges=rep["n_edges"], n_lock_series=len(lock_series),
                max_hold_p99_us=args.max_hold_p99_us)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default="HOSTLINT_BASELINE.json")
    ap.add_argument("--update", action="store_true",
                    help="rewrite the baseline instead of failing on "
                    "drift (the shipped table stays empty — use for "
                    "consciously-staged transitions only)")
    ap.add_argument("--out", default=None, metavar="FINDINGS_JSONL",
                    help="export static findings as obs-schema JSONL")
    ap.add_argument("--static-only", action="store_true",
                    help="skip the dynamic red + soak legs (fast "
                    "pre-commit mode)")
    ap.add_argument("--max-hold-p99-us", type=float, default=500_000.0,
                    help="soak bound on any single lock's hold-time p99")
    ap.add_argument("--soak-batches", type=int, default=24)
    ap.add_argument("--warmup-batches", type=int, default=3)
    args = ap.parse_args(argv)

    from hermes_tpu import analysis as ana
    from hermes_tpu.analysis import hostlint, lockgraph

    legs = {}

    def run_leg(name, fn, *a):
        t0 = time.perf_counter()
        try:
            r = fn(*a)
        except Exception as e:  # noqa: BLE001 — a crashed leg is a
            # failed leg with the exception as its report, never a
            # silently green gate
            r = dict(ok=False, error=f"{type(e).__name__}: {e}")
        r["seconds"] = round(time.perf_counter() - t0, 2)
        legs[name] = r
        print(f"[hostlint] {name}: {'ok' if r['ok'] else 'FAIL'} "
              f"in {r['seconds']}s", file=sys.stderr)

    run_leg("static", leg_static, args, ana, hostlint)
    run_leg("red_static", leg_red_static, ana, hostlint)
    if not args.static_only:
        run_leg("red_dynamic", leg_red_dynamic, lockgraph)
        run_leg("soak", leg_soak, args, lockgraph)

    ok = all(leg["ok"] for leg in legs.values())
    st = legs["static"]
    print(json.dumps(dict(
        ok=ok, errors=st.get("errors", -1),
        warnings=st.get("warnings", -1), infos=st.get("infos", -1),
        gating_sites=st.get("gating_sites", -1),
        new_findings=st.get("new_findings", []),
        stale_baseline=st.get("stale_baseline", []),
        legs=legs)))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
