"""Checked bench window (VERDICT round-1 item 4, second half): run the
bench-shaped workload WITH the columnar history recorder, then run the
native witness linearizability check over the full >=10M-op history, and
report both the recording overhead and the checking rate.

    python scripts/checked_bench.py [--rounds 30] [--out CHECKED_BENCH.json]

The throughput bench (bench.py) runs scan-chunked with recording off; this
harness answers "does the engine stay linearizable at bench scale, and how
fast can we prove it" — completions are fetched per round (recording
requires them), so the per-round link handshake dominates wall time here.
"""

import argparse
import json
import sys
import time

sys.path.insert(0, ".")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=30)
    ap.add_argument("--sessions", type=int, default=32768)
    # --mix zipfian gates the contended config-3 path (deep production
    # chains, bench default chain_writes=2048) under the real checker;
    # --mix rmw gates the round-5 retry-in-place RMW path the same way
    ap.add_argument("--mix", choices=("a", "rmw", "zipfian"), default="a")
    ap.add_argument("--out", default="CHECKED_BENCH.json")
    args = ap.parse_args()

    import jax

    import bench
    from hermes_tpu.runtime import FastRuntime

    # the EXACT bench shape (bench._cfg is the single source of truth),
    # at a recordable session count
    cfg = bench._cfg(args.mix, over=dict(
        n_sessions=args.sessions, lane_budget_cfg=(3 * args.sessions) // 4))
    rt = FastRuntime(cfg, record="array")

    # warm up: one round compiles + switches the tunneled link to
    # synchronous mode (bench.py's measurement protocol), so the timed
    # window measures steady-state recording, not compilation
    rt.run(1)
    jax.block_until_ready(rt.fs)
    c_warm = rt.counters()

    t0 = time.perf_counter()
    rt.run(args.rounds)
    jax.block_until_ready(rt.fs)
    counters = rt.counters()  # forces the deferred tunnel work
    run_wall = time.perf_counter() - t0

    t1 = time.perf_counter()
    verdict = rt.check()  # ALL keys, native witness core (checker/fast.py)
    check_wall = time.perf_counter() - t1
    # the op population the checker actually processed (finalized columns:
    # NOP and aborted-RMW rows dropped, in-flight maybe_w rows added)
    n_ops = int(rt.recorder.columns()["kind"].shape[0])

    out = {
        "mix": args.mix,
        "chain_writes": cfg.chain_writes,
        "rmw_retries": cfg.rmw_retries,
        "rounds": args.rounds,
        "aborts": int(counters["n_abort"] - c_warm["n_abort"]),
        "ops_checked": n_ops,
        "writes_committed": int(counters["n_write"] + counters["n_rmw"]
                                - c_warm["n_write"] - c_warm["n_rmw"]),
        "run_wall_s": round(run_wall, 2),
        "recorded_ops_per_sec": round(n_ops / run_wall, 1),
        "check_wall_s": round(check_wall, 2),
        "check_ops_per_sec": round(n_ops / check_wall, 1),
        **verdict.to_dict(),
        "platform": jax.devices()[0].platform,
        "device": getattr(jax.devices()[0], "device_kind", "?"),
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
