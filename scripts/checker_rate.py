"""Host-side checking-rate benchmark for the native witness core.

Synthesizes a well-formed ~10M-op columnar history (every read referencing
a real write uid, versions monotone per key) and times
``checker.fast.check_arrays`` end to end — the pure checking rate,
independent of where the history came from.  The integrated on-chip
artifact is scripts/checked_bench.py; this harness isolates the checker
itself (and its exact-search fallback behavior when --spoil injects
violations).

    python scripts/checker_rate.py [--ops 10000000] [--spoil 0]

Measured 2026-07-30 (this container's host CPU): ~925k ops/s over a 9.76M-op
1-write-per-key-per-step history across 262k keys, verdict PASS, zero
fallback.  A pathological history (every key failing, full exact-search
fallback) degrades to ~127k ops/s — the witness-then-exact design pays the
expensive path only on suspect keys.
"""

import argparse
import pathlib
import sys
import time

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from hermes_tpu.config import HermesConfig
from hermes_tpu.checker.fast import ArrayRecorder, check_arrays
from hermes_tpu.core import types as t


def synthesize(rec, K, n_ops, spoil, seed=0):
    rng = np.random.default_rng(seed)

    def emit(keys, is_w, ver, step):
        n = keys.shape[0]
        h = ((keys.astype(np.int64) << 21) ^ ver).astype(np.int64)
        lo = (h & 0x7FFFFFFF).astype(np.int32)
        hi = ((h >> 31) & 0x7FFFFFFF).astype(np.int32)

        class Comp:
            pass

        comp = Comp()
        comp.code = np.where(is_w, t.C_WRITE, t.C_READ).astype(np.int32)
        comp.key = keys
        comp.wval = np.stack([lo, hi] + [np.zeros(n, np.int32)] * 6, axis=1)
        rlo = lo.copy()
        if spoil:
            # corrupt a fraction of read values: uid of a never-written
            # version — the witness flags the key, the exact search confirms
            bad = rng.random(n) < spoil
            rlo = np.where(bad & ~is_w, rlo ^ 0x5A5A5A, rlo)
        comp.rval = np.stack([rlo, hi] + [np.zeros(n, np.int32)] * 6, axis=1)
        comp.ver = ver.astype(np.int64)
        comp.fc = np.zeros(n, np.int64)
        comp.invoke_step = np.full(n, step, np.int64)
        comp.commit_step = np.full(n, step, np.int64)
        rec.record_step(comp)

    emit(np.arange(K, dtype=np.int32), np.ones(K, bool),
         np.ones(K, np.int64), 0)
    ver_ctr = np.ones(K, np.int64)
    CH = 500_000
    for c in range((n_ops - K) // CH):
        keys = rng.integers(0, K, CH).astype(np.int32)
        wsel = np.where(rng.random(CH) < 0.5)[0]
        _, first_idx = np.unique(keys[wsel], return_index=True)
        is_w = np.zeros(CH, bool)
        is_w[wsel[first_idx]] = True  # one write per key per step
        ver = ver_ctr[keys].copy()
        ver[is_w] += 1
        ver_ctr[keys[is_w]] += 1
        ver[~is_w] = ver_ctr[keys[~is_w]]
        emit(keys, is_w, ver, c + 1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=10_000_000)
    ap.add_argument("--keys", type=int, default=1 << 18)
    ap.add_argument("--spoil", type=float, default=0.0,
                    help="fraction of reads corrupted (exercises the exact "
                         "fallback; verdict must then FAIL)")
    args = ap.parse_args()

    cfg = HermesConfig(n_replicas=8, n_keys=args.keys, n_sessions=1024,
                       ops_per_session=256, value_words=8)
    rec = ArrayRecorder(cfg)
    t0 = time.perf_counter()
    synthesize(rec, args.keys, args.ops, args.spoil)
    gen = time.perf_counter() - t0
    n = rec.n_recorded
    t1 = time.perf_counter()
    v = check_arrays(rec)
    wall = time.perf_counter() - t1
    import json
    print(json.dumps({
        "ops": n, "gen_s": round(gen, 2), "check_s": round(wall, 2),
        "check_ops_per_sec": round(n / wall, 1),
        "verdict_ok": v.ok, "keys_checked": v.keys_checked,
        "failing_keys": len(v.failures), "undecided_keys": len(v.undecided),
    }))


if __name__ == "__main__":
    main()
