"""Round-22 durability-soak CHILD: the process scripts/check_durability.py
kill -9s.

Drives put waves at 2x the store's in-flight capacity against a WAL-backed
KVS (``wal_sync='commit'``: a future resolves only after its group-commit
fsync), appends one JSON line per CLIENT-OBSERVED commit to the commits
file, and dies by its own schedule: a ``powercut`` chaos verb fires
mid-wave — with ops in flight and the dirty window non-empty — through a
carrier that SIGKILLs this very process.  No flush, no close, no atexit:
the exact crash shape the WAL exists for.

The commits file is the parent's witness set: every line is a write some
client saw resolve ``committed``, so after recovery every line's uid MUST
appear as a definite committed write in the replayed log
(checker.linearizability.committed_write_lost == []).  Lines are written
only AFTER resolution and flushed per wave; lines lost in the kill only
shrink the checked set (under-approximation — never a false pass).

    python scripts/_durability_soak.py WAL_DIR BACKEND COMMITS_JSONL KILL_WAVE

``KILL_WAVE < 0`` disables the powercut (the wal-overhead leg reuses the
same drive loop in-process via ``run_waves``).
"""

from __future__ import annotations

import itertools
import json
import os
import signal
import sys

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

SEED = 29
N_REPLICAS = 3
PAYLOAD_WORDS = 4  # value_words = 2 uid words + payload


def soak_cfg(wal_dir, wal_sync="commit"):
    """ONE config for child and parent: chaos.recovery.recover_store
    refuses a header mismatch, and the parent's replay must land in an
    identically-shaped table."""
    from hermes_tpu.config import HermesConfig, WorkloadConfig

    return HermesConfig(
        n_replicas=N_REPLICAS, n_keys=128, n_sessions=8, replay_slots=8,
        value_words=2 + PAYLOAD_WORDS, ops_per_session=64,
        pipeline_depth=2, wal_dir=wal_dir, wal_sync=wal_sync,
        workload=WorkloadConfig(seed=SEED),
    )


def build_kvs(wal_dir, backend, wal_sync="commit"):
    import jax
    import numpy as np

    from hermes_tpu.kvs import KVS

    mesh = None
    if backend == "sharded":
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:N_REPLICAS]), ("replica",))
    return KVS(soak_cfg(wal_dir, wal_sync), backend=backend, mesh=mesh)


def run_waves(kvs, waves, on_commit=None, on_wave=None, rng_seed=SEED):
    """The shared drive loop: per wave, submit 2x-capacity unique-payload
    puts, optionally interrupt mid-flight (``on_wave`` — the powercut
    hook), resolve, and report each committed put.  Returns the number of
    committed writes."""
    import numpy as np

    rng = np.random.default_rng(rng_seed)
    cfg = kvs.cfg
    n = 2 * cfg.n_replicas * cfg.n_sessions  # 2x in-flight capacity
    committed = 0
    it = range(waves) if waves >= 0 else itertools.count()
    for wave in it:
        keys = rng.integers(0, cfg.n_keys, n)
        vals = np.empty((n, PAYLOAD_WORDS), np.int32)
        vals[:, 0] = wave
        vals[:, 1] = np.arange(n)
        vals[:, 2:] = rng.integers(0, 1 << 20, (n, PAYLOAD_WORDS - 2))
        bf = kvs.submit_batch(np.full(n, kvs.PUT, np.int32), keys, vals)
        for _ in range(3):
            kvs.step()  # get the wave genuinely in flight ...
        if on_wave is not None:
            on_wave(wave)  # ... THEN let the adversary at it
        assert kvs.run_batch(bf), "soak wave did not resolve"
        for i in range(n):
            c = bf.completion(i)
            if c.kind == "put":
                committed += 1
                if on_commit is not None:
                    on_commit(c, wave)
            else:
                assert c.kind == "retry_after", (
                    f"unexpected completion {c.kind} for a put")
    return committed


def main(argv) -> int:
    wal_dir, backend, commits_path, kill_wave = (
        argv[0], argv[1], argv[2], int(argv[3]))
    kvs = build_kvs(wal_dir, backend)
    out = open(commits_path, "w")

    def carrier(step):
        # the client's observations survive; the store's do not — that
        # asymmetry is exactly what the parent checks
        out.flush()
        os.fsync(out.fileno())
        os.kill(os.getpid(), signal.SIGKILL)

    from hermes_tpu import chaos

    sched = chaos.Schedule([chaos.ChaosEvent(step=kill_wave,
                                             kind="powercut")])
    runner = chaos.ChaosRunner(kvs, sched, powercut=carrier)

    def on_commit(c, wave):
        out.write(json.dumps(dict(uid=list(c.uid), key=c.key,
                                  ts=list(c.ts), wave=wave,
                                  durability=c.durability)) + "\n")

    def on_wave(wave):
        out.flush()
        runner.tick(wave)  # fires the powercut at kill_wave — no return

    if kill_wave >= 0:
        run_waves(kvs, -1, on_commit=on_commit, on_wave=on_wave)
        raise AssertionError("powercut never fired")  # pragma: no cover
    run_waves(kvs, 4, on_commit=on_commit)
    out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
