"""Sweep (n_sessions, lane_budget) of the bench config on the real chip.

Same measurement protocol as bench.py (warmup readback forces the tunneled
runtime into synchronous mode; then timed scan-chunks).  Usage:

    python scripts/sweep_bench.py S:C [S:C ...]   # C may be 'full'
"""

import sys
import time

sys.path.insert(0, ".")

import jax

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import faststep as fst
from hermes_tpu.workload import ycsb

ROUNDS = 50
CHUNKS = 2


def run(S, C):
    cfg = HermesConfig(
        n_replicas=8, n_keys=1 << 20, value_words=8, n_sessions=S,
        replay_slots=256, ops_per_session=256, wrap_stream=True,
        device_stream=True,
        lane_budget_cfg=None if C == "full" else C,
        rebroadcast_every=4, replay_scan_every=32,
        workload=WorkloadConfig(read_frac=0.5, seed=0),
    )
    fs = jax.device_put(fst.init_fast_state(cfg))
    stream = jax.device_put(fst.prep_stream(ycsb.stub_stream(cfg)))
    chunk = fst.build_fast_scan(cfg, ROUNDS, donate=True)

    def counters(x):
        m = jax.device_get(x.meta)
        return int(m.n_write.sum() + m.n_rmw.sum())

    fs = chunk(fs, stream, fst.make_fast_ctl(cfg, 0))
    jax.block_until_ready(fs)
    c0 = counters(fs)

    t0 = time.perf_counter()
    for c in range(1, 1 + CHUNKS):
        fs = chunk(fs, stream, fst.make_fast_ctl(cfg, c * ROUNDS))
    jax.block_until_ready(fs)
    t1 = time.perf_counter()

    commits = counters(fs) - c0
    wall = t1 - t0
    rounds = CHUNKS * ROUNDS
    print(
        f"S={S:7d} C={cfg.lane_budget:7d}  "
        f"round={wall / rounds * 1e3:8.2f} ms  "
        f"commits/round={commits / rounds:9.0f}  "
        f"wps={commits / wall / 1e6:6.2f} M/s",
        flush=True,
    )


if __name__ == "__main__":
    for spec in sys.argv[1:]:
        s, c = spec.split(":")
        run(int(s), c if c == "full" else int(c))
