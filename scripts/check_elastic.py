"""Round-10 elastic-operations gate (CI): live resize, key-range
migration, and the rolling-restart drill must hold their contracts on
every change.

Four assertions, CPU-smoke sized (joins check_op_census.py,
check_obs_overhead.py, check_analysis.py, check_pipeline.py and
check_chaos.py in the verify flow — the SIX gates run SERIALLY, never
beside pytest: the obs-overhead gate is contention-sensitive):

  1. rolling-restart drill — every replica of an 8-replica group is
     crash-restarted in sequence under depth-2 pipelined load
     (hermes_tpu.elastic.run_rolling_restart) on BOTH engines: all 8
     restarts apply, the cluster drains, the linearizability checker
     passes with zero violations, and the worst-window throughput dip is
     measured and recorded (dip_pct);
  2. drill determinism — the same seed + config replays the rolling
     drill to a byte-identical executed-event log and final state tree;
  3. live resize — every replica shrunk (fence + client drain + quorum
     remove) and grown (join value sync) in sequence through the KVS
     under standing client load, both engines, checker-gated; ops routed
     at a retired replica land as kind='rejected', never stranded;
  4. live key-range migration — the composed drill
     (hermes_tpu.elastic.migration_drill: fence → drain → snapshot →
     transfer → flip → release) under depth-2 load, both engines plus a
     sparse-key (KeyIndex remap) cell: post-flip destination reads serve
     the migrated values, boundary routing is exact at lo/hi-1,
     mid-drain ops land rejected/salvaged (never dropped), and BOTH
     groups' histories pass the checker.

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/check_elastic.py

Prints one JSON line (also written to ELASTIC_SOAK.json); exit non-zero
on any violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

SEED = 31
ROLL_START, ROLL_SPACING = 4, 10


def _drill_cfg(**over):
    from hermes_tpu.config import HermesConfig, WorkloadConfig

    kw = dict(
        n_replicas=8, n_keys=128, n_sessions=4, replay_slots=6,
        ops_per_session=96, value_words=6, replay_age=6,
        replay_scan_every=4, rebroadcast_every=2, lease_steps=6,
        pipeline_depth=2,
        workload=WorkloadConfig(read_frac=0.4, rmw_frac=0.2, seed=SEED),
    )
    kw.update(over)
    return HermesConfig(**kw)


def _mesh():
    import jax
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices()[:8]), ("replica",))


def _rolling(backend):
    from hermes_tpu import elastic
    from hermes_tpu.runtime import FastRuntime

    cfg = _drill_cfg()
    rt = FastRuntime(cfg, backend=backend,
                     mesh=_mesh() if backend == "sharded" else None,
                     record=True)
    res = elastic.run_rolling_restart(
        rt, start=ROLL_START, spacing=ROLL_SPACING, check=True)
    return rt, res


def check_rolling(report: dict) -> None:
    for backend in ("batched", "sharded"):
        rt, res = _rolling(backend)
        assert res["restarts"] == rt.cfg.n_replicas, (
            f"{backend}: only {res['restarts']}/{rt.cfg.n_replicas} "
            "replicas restarted")
        assert res["drained"], f"{backend}: did not drain after the drill"
        assert res["checked_ok"], (
            f"{backend}: checker FAIL {res.get('check_failures')}")
        dip = res["dip"]
        assert dip["dip_pct"] is not None and dip["windows"] > 0, dip
        report[f"{backend}_rolling"] = dict(
            restarts=res["restarts"], lost_ops=res["lost_ops"],
            checked_ok=True, dip_pct=dip["dip_pct"],
            worst_window=dip["worst_window"])


def check_determinism(report: dict) -> None:
    import jax
    import numpy as np

    logs, states = [], []
    for _ in range(2):
        from hermes_tpu import chaos
        from hermes_tpu import elastic
        from hermes_tpu.runtime import FastRuntime

        cfg = _drill_cfg()
        rt = FastRuntime(cfg, record=True)
        sched = chaos.Schedule.rolling_restart(cfg, start=ROLL_START,
                                               spacing=ROLL_SPACING)
        runner = chaos.ChaosRunner(
            rt, sched, spec=chaos.ChaosSpec(min_healthy=2))
        res = runner.run(ROLL_START + ROLL_SPACING * (cfg.n_replicas + 1),
                         check=True)
        assert res["checked_ok"], res
        logs.append(runner.log_json())
        states.append(jax.tree.leaves(jax.device_get(rt.fs)))
    assert logs[0] == logs[1], "rolling drill executed logs differ"
    for x, y in zip(states[0], states[1]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    report["deterministic_replay"] = True


def check_resize(report: dict) -> None:
    from hermes_tpu import elastic
    from hermes_tpu.kvs import KVS

    for backend in ("batched", "sharded"):
        cfg = _drill_cfg()
        kvs = KVS(cfg, backend=backend,
                  mesh=_mesh() if backend == "sharded" else None,
                  record=True)
        # size the standing load to outlast the whole drill (~R cycles of
        # 2*hold_steps rounds plus per-cycle drains, up to R*S completions
        # per round), so every sampled window measures service under load,
        # not load exhaustion
        rounds_est = cfg.n_replicas * (2 * 8 + 6) + 24
        bf = elastic.submit_drill_mix(
            kvs, rounds_est * cfg.n_replicas * cfg.n_sessions, seed=SEED)
        res = elastic.rolling_resize(kvs, check=True)
        assert kvs.run_batch(bf), f"{backend}: standing load stranded"
        assert res["resizes"] == cfg.n_replicas, res
        assert res["checked_ok"], (
            f"{backend}: resize checker FAIL {res.get('check_failures')}")
        # a retired replica rejects loudly, then serves again after grow
        kvs.shrink(0)
        f = kvs.put(0, 0, 1, [7])
        assert f.done() and f.result().kind == "rejected"
        kvs.grow(0)
        f = kvs.put(0, 0, 1, [7])
        assert kvs.run_until([f]) and f.result().kind == "put"
        report[f"{backend}_resize"] = dict(
            resizes=res["resizes"], rejected_ops=res["rejected_ops"],
            checked_ok=True, dip_pct=res["dip"]["dip_pct"])


def check_migration(report: dict) -> None:
    from hermes_tpu import elastic
    from hermes_tpu.kvs import KVS

    for backend in ("batched", "sharded"):
        cfg = _drill_cfg()
        res = elastic.migration_drill(
            cfg, backend=backend,
            mesh=_mesh() if backend == "sharded" else None,
            record=True, seed=SEED, check=True)
        assert res["src_checked_ok"] and res["dst_checked_ok"], res
        report[f"{backend}_migration"] = dict(
            rows=res["rows"], rejected=res["live_rejected"],
            salvaged=res["salvaged"], drained=res["drained"],
            checked_ok=True)

    # sparse-key remap cell (batched): client keys keep resolving through
    # the destination's KeyIndex after the flip
    from hermes_tpu.config import WorkloadConfig

    cfg = _drill_cfg(n_keys=64, n_replicas=4,
                     workload=WorkloadConfig(seed=SEED))
    src = KVS(cfg, record=True, sparse_keys=True)
    dst = KVS(cfg, record=True, sparse_keys=True)
    keys = [(i + 1) * 10**12 for i in range(12)]
    futs = [src.put(i % 4, i % 4, k, [i]) for i, k in enumerate(keys)]
    assert src.run_until(futs)
    res = elastic.migrate_range(src, dst, 4, 10)
    for i in range(4, 10):
        g = dst.get(0, 0, keys[i])
        assert dst.run_until([g]) and g.result().value[:1] == [i], i
    assert src.rt.check().ok and dst.rt.check().ok
    report["sparse_migration"] = dict(rows=res["rows"], checked_ok=True)


def main() -> int:
    report: dict = {"gate": "elastic"}
    try:
        check_rolling(report)
        check_determinism(report)
        check_resize(report)
        check_migration(report)
    except AssertionError as e:
        report["ok"] = False
        report["error"] = str(e)
        print(json.dumps(report, default=str))
        return 1
    report["ok"] = True
    out = os.path.join(os.path.dirname(__file__), "..", "ELASTIC_SOAK.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True, default=str)
        f.write("\n")
    print(json.dumps(report, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
