"""Round-9 chaos & recovery gate (CI): the fault-injection subsystem must
hold its three contracts on every change.

Four assertions, CPU-smoke sized (joins scripts/check_op_census.py,
check_obs_overhead.py, check_analysis.py and check_pipeline.py in the
verify flow):

  1. composed chaos soak — a seeded schedule of freeze / thaw / join /
     crash-restart / heartbeat clock-skew, with the failure detector
     attached (confirm window > 0), against FastRuntime at
     ``pipeline_depth=2`` on BOTH engines: the linearizability checker
     passes with zero violations, op totals conserve against the crash
     losses, and the obs trace shows ZERO ``membership_fetch`` events —
     the detector rides the completion harvest, never the dispatch path
     (the ``ctl_upload`` regression pattern, applied to detection);
  2. schedule determinism — the same seed + config replays to a
     byte-identical executed-event log and final state tree;
  3. torn-snapshot red test — a bit-flipped archive is rejected loudly by
     the manifest checksum, a truncated one by the targeted
     incompleteness checks, and save leaves no temp files behind;
  4. sim-engine net chaos — drop / delay / duplicate windows
     (chaos.NetChaos) composed with freezes on the host-mediated engine,
     checker-gated.

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/check_chaos.py

Prints one JSON line (also written to CHAOS_SOAK.json); exit non-zero on
any violation.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

SEED = 23
STEPS = 220


def _soak_cfg(pipeline_depth=2):
    from hermes_tpu.config import HermesConfig, WorkloadConfig

    return HermesConfig(
        n_replicas=5, n_keys=96, n_sessions=6, replay_slots=6,
        ops_per_session=24, replay_age=6, replay_scan_every=4,
        rebroadcast_every=2, lease_steps=6, pipeline_depth=pipeline_depth,
        workload=WorkloadConfig(read_frac=0.4, rmw_frac=0.25, seed=SEED),
    )


def _run_soak(backend, mesh=None):
    from hermes_tpu import chaos
    from hermes_tpu.membership import MembershipService
    from hermes_tpu.obs import Observability
    from hermes_tpu.runtime import FastRuntime

    cfg = _soak_cfg()
    rt = FastRuntime(cfg, backend=backend, mesh=mesh, record=True)
    obs = rt.attach_obs(Observability())
    rt.attach_membership(MembershipService(cfg, confirm_steps=3))
    sched = chaos.Schedule.random(cfg, seed=SEED, steps=STEPS,
                                  spec=chaos.ChaosSpec(p_crash=0.03))
    runner = chaos.ChaosRunner(rt, sched)
    res = runner.run(STEPS, check=True)
    ev = [r.get("name") for r in obs.records if r.get("kind") == "event"]
    return rt, runner, res, ev


def check_soak(report: dict) -> None:
    import jax
    import numpy as np

    for backend in ("batched", "sharded"):
        mesh = None
        if backend == "sharded":
            from jax.sharding import Mesh

            mesh = Mesh(np.array(jax.devices()[:5]), ("replica",))
        rt, runner, res, ev = _run_soak(backend, mesh)
        assert res["drained"], f"{backend}: cluster did not drain"
        assert res["checked_ok"], (
            f"{backend}: checker FAIL {res['check_failures']}")
        assert ev.count("membership_fetch") == 0, (
            f"{backend}: detector issued {ev.count('membership_fetch')} "
            "synchronous last_seen fetch(es) on the dispatch path")
        assert "suspect" in ev and "remove" in ev, (
            f"{backend}: detector never fired under the schedule ({ev})")
        applied = {e["kind"] for e in runner.log}
        assert "crash_restart" in applied, (
            f"{backend}: schedule applied no crash_restart ({applied})")
        c = rt.counters()
        total = c["n_read"] + c["n_write"] + c["n_rmw"] + c["n_abort"]
        expect = rt.cfg.n_replicas * rt.cfg.n_sessions * rt.cfg.ops_per_session
        assert total == expect - res["lost_ops"], (
            f"{backend}: totals {total} != {expect} - lost {res['lost_ops']}")
        report[f"{backend}_soak"] = dict(
            events=len(runner.log), lost_ops=res["lost_ops"],
            suspects=ev.count("suspect"), removes=ev.count("remove"),
            checked_ok=True, membership_fetches=0)


def check_determinism(report: dict) -> None:
    import jax
    import numpy as np

    logs, states = [], []
    for _ in range(2):
        rt, runner, res, _ev = _run_soak("batched")
        assert res["checked_ok"]
        logs.append(runner.log_json())
        states.append(jax.tree.leaves(jax.device_get(rt.fs)))
    assert logs[0] == logs[1], "executed-event logs differ across replays"
    for x, y in zip(states[0], states[1]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    report["deterministic_replay"] = True


def check_torn_snapshot(report: dict) -> None:
    import zipfile

    import numpy as np

    from hermes_tpu import snapshot
    from hermes_tpu.runtime import FastRuntime

    cfg = _soak_cfg(pipeline_depth=1)
    rt = FastRuntime(cfg)
    rt.run(6)
    d = tempfile.mkdtemp()
    p = os.path.join(d, "snap.npz")
    snapshot.save(p, rt)
    assert not [f for f in os.listdir(d) if ".tmp" in f], "temp file left"

    # bit-flip one payload byte inside a state member -> checksum reject
    torn = os.path.join(d, "torn.npz")
    with zipfile.ZipFile(p) as zin, zipfile.ZipFile(torn, "w") as zout:
        for name in zin.namelist():
            data = bytearray(zin.read(name))
            if name.startswith("state.table.bank"):
                data[len(data) // 2] ^= 0xFF
            zout.writestr(name, bytes(data))
    tgt = FastRuntime(cfg)
    try:
        snapshot.load(torn, tgt)
        raise AssertionError("torn snapshot must be rejected")
    except ValueError as e:
        assert "checksum" in str(e) or "torn" in str(e), str(e)
    report["torn_snapshot_rejected"] = True

    # truncated (missing member) -> targeted incompleteness reject
    trunc = os.path.join(d, "trunc.npz")
    with zipfile.ZipFile(p) as zin, zipfile.ZipFile(trunc, "w") as zout:
        victims = [n for n in zin.namelist() if n.startswith("state.sess")]
        for name in zin.namelist():
            if name != victims[0]:
                zout.writestr(name, zin.read(name))
    try:
        snapshot.load(trunc, FastRuntime(cfg))
        raise AssertionError("truncated snapshot must be rejected")
    except ValueError as e:
        assert "incomplete" in str(e), str(e)
    report["truncated_snapshot_rejected"] = True

    # and the happy path restores bit-exact
    tgt = FastRuntime(cfg)
    snapshot.load(p, tgt)
    import jax

    np.testing.assert_array_equal(
        np.asarray(jax.device_get(rt.fs.table.vpts)),
        np.asarray(jax.device_get(tgt.fs.table.vpts)))
    report["snapshot_roundtrip"] = True


def check_net_chaos_sim(report: dict) -> None:
    from hermes_tpu import chaos
    from hermes_tpu.config import HermesConfig, WorkloadConfig
    from hermes_tpu.runtime import Runtime
    from hermes_tpu.transport.sim import SimTransport

    cfg = HermesConfig(
        n_replicas=4, n_keys=64, n_sessions=4, replay_slots=8,
        ops_per_session=20, replay_age=5, lease_steps=6,
        workload=WorkloadConfig(read_frac=0.4, rmw_frac=0.2, seed=SEED),
    )
    net = chaos.NetChaos()
    rt = Runtime(cfg, backend="sim", record=True,
                 transport=SimTransport(cfg.n_replicas, net))
    sched = chaos.Schedule.parse("""
        @5  net_drop 0 dst=2 until=25
        @10 net_delay 1 skew=3 until=40
        @15 net_dup 2 until=35
        @20 freeze 3
        @30 thaw 3
    """)
    runner = chaos.ChaosRunner(rt, sched, net=net)
    res = runner.run(60, check=True)
    assert res["drained"], "sim net-chaos run did not drain"
    assert res["checked_ok"], f"sim net-chaos checker FAIL: {res}"
    applied = {e["kind"] for e in runner.log}
    assert {"net_drop", "net_delay", "net_dup"} <= applied, applied
    report["sim_net_chaos"] = dict(events=len(runner.log), checked_ok=True)


def main() -> int:
    report: dict = {"gate": "chaos"}
    try:
        check_soak(report)
        check_determinism(report)
        check_torn_snapshot(report)
        check_net_chaos_sim(report)
    except AssertionError as e:
        report["ok"] = False
        report["error"] = str(e)
        print(json.dumps(report))
        return 1
    report["ok"] = True
    out = os.path.join(os.path.dirname(__file__), "..", "CHAOS_SOAK.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
