"""Round-11 adversarial wire chaos gate (CI): the transport-generic fault
interposer, the CRC'd frame layer, and partition tolerance must hold their
contracts on every change.

Five assertions, CPU-smoke sized (the SEVENTH gate — joins census,
obs-overhead, analysis, pipeline, chaos and elastic in the verify flow;
scripts/run_gates.py runs all of them serially):

  1. wire-matrix soak — a seeded schedule of drop / duplicate / reorder /
     delay / corrupt / asymmetric-partition windows (chaos.net.
     FaultingTransport) composed with freezes, on the sim engine with the
     failure detector attached: the linearizability checker passes, every
     fault class actually fired, a partitioned replica was ejected and
     rejoined through the epoch-fenced join, and NO corrupted frame was
     ever applied (CRC downgraded every one to a drop);
  2. transport-generic — the SAME interposer and schedule over a different
     inner transport (the lockstep loopback), checker-gated: the adversary
     is not welded to the sim transport;
  3. determinism — same seed + config replays a byte-identical executed
     fault log (runner events + wire fault log) AND final state;
  4. CRC red test — a corrupted frame is rejected by codec.frame_unpack,
     and the crc=False interposer path proves the damage would otherwise
     reach the protocol (scrambled bytes delivered);
  5. partition tolerance at pipeline depth 2, BOTH fast engines — a
     KVS(depth=2) run under partition/heal schedules with the detector
     attached: every client future resolves despite the adversary (bounded
     retry re-routes ops wedged on the ejected replica), the checker
     passes, and no committed-and-observed write is ever reported
     lost/aborted across the partition+heal cycle
     (lin.committed_write_lost == []).

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/check_netchaos.py

Prints one JSON line (also written to NETCHAOS_SOAK.json); exit non-zero on
any violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

SEED = 31
STEPS = 80


def _wire_cfg():
    from hermes_tpu.config import HermesConfig, WorkloadConfig

    return HermesConfig(
        n_replicas=4, n_keys=64, n_sessions=4, replay_slots=8,
        ops_per_session=16, replay_age=5, lease_steps=6,
        workload=WorkloadConfig(read_frac=0.4, rmw_frac=0.2, seed=SEED),
    )


WIRE_SCHEDULE = """
    @4  netdrop 0 dst=2 until=24
    @6  netdelay 1 skew=2 until=30
    @8  netdup 2 until=26
    @10 netreorder 3 dst=0 skew=3 until=32
    @12 netcorrupt 1 dst=3 until=28
    @16 partition 2 until=40        # asymmetric: 2's outbound goes dark
    @20 freeze 3
    @28 thaw 3
    @44 heal
"""


def _run_wire(inner_kind: str):
    from hermes_tpu import chaos
    from hermes_tpu.membership import MembershipService
    from hermes_tpu.runtime import Runtime
    from hermes_tpu.transport.base import LockstepHostTransport
    from hermes_tpu.transport.sim import SimTransport

    cfg = _wire_cfg()
    inner = (SimTransport(cfg.n_replicas) if inner_kind == "sim"
             else LockstepHostTransport())
    wire = chaos.FaultingTransport(inner, cfg.n_replicas, seed=SEED)
    rt = Runtime(cfg, backend="sim", record=True, transport=wire)
    rt.attach_membership(MembershipService(cfg, confirm_steps=2))
    sched = chaos.Schedule.parse(WIRE_SCHEDULE)
    runner = chaos.ChaosRunner(rt, sched, wire=wire)
    res = runner.run(64, check=True)
    return rt, wire, runner, res


def check_wire_matrix(report: dict) -> None:
    for inner_kind in ("sim", "lockstep"):
        rt, wire, runner, res = _run_wire(inner_kind)
        assert res["drained"], f"{inner_kind}: did not drain"
        assert res["checked_ok"], (
            f"{inner_kind}: checker FAIL {res['check_failures']}")
        c = wire.counters
        for op in ("drop", "delay", "dup", "reorder", "partition"):
            assert c.get(f"wire_{op}", 0) > 0, (
                f"{inner_kind}: fault class {op} never fired ({dict(c)})")
        assert c.get("wire_corrupt", 0) > 0, f"{inner_kind}: no corruption"
        assert c.get("wire_corrupt_dropped", 0) == c["wire_corrupt"], (
            f"{inner_kind}: corrupt frames not all dropped ({dict(c)})")
        assert c.get("wire_corrupt_applied", 0) == 0, (
            f"{inner_kind}: a corrupted frame was APPLIED")
        mem = [(e.kind, e.replica) for e in rt.membership.events]
        assert ("remove", 2) in mem and ("join", 2) in mem, (
            f"{inner_kind}: partitioned replica not ejected+rejoined {mem}")
        report[f"wire_{inner_kind}"] = dict(
            events=len(runner.log), faults=dict(c),
            membership=[f"{k}:{r}" for k, r in mem], checked_ok=True)


def check_determinism(report: dict) -> None:
    import jax
    import numpy as np

    logs, states = [], []
    for _ in range(2):
        rt, wire, runner, res = _run_wire("sim")
        assert res["checked_ok"]
        logs.append(runner.log_json() + "\n" + wire.fault_log_json())
        states.append(jax.tree.leaves(jax.device_get(rt.rs)))
    assert logs[0] == logs[1], "executed fault logs differ across replays"
    for x, y in zip(states[0], states[1]):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    report["deterministic_replay"] = True


def check_crc_red(report: dict) -> None:
    import numpy as np

    from hermes_tpu import chaos
    from hermes_tpu.core import state as st
    from hermes_tpu.transport import codec
    from hermes_tpu.transport.base import LockstepHostTransport

    # codec level: a flipped payload byte must be REJECTED
    payload = np.arange(256, dtype=np.uint8)
    frame = codec.frame_pack(payload)
    np.testing.assert_array_equal(codec.frame_unpack(frame), payload)
    torn = frame.copy()
    torn[codec.FRAME_OVERHEAD + 40] ^= 0x01
    try:
        codec.frame_unpack(torn)
        raise AssertionError("corrupted frame passed the checksum")
    except codec.FrameCorrupt:
        pass

    # interposer level: with CRC the corrupted pair frame is NEVER applied
    # (zero block); without it the scramble reaches the protocol — the red
    # half that proves what the checksum is for
    cfg = _wire_cfg()
    out = st.empty_invs(cfg, lead=(cfg.n_replicas,))
    out = out._replace(
        valid=np.ones_like(np.asarray(out.valid)),
        key=np.full_like(np.asarray(out.key), 7),
        alive=np.ones_like(np.asarray(out.alive)))
    clean = {f: np.asarray(v)[1, 0]  # dst=1, src=0 pair, unfaulted
             for f, v in LockstepHostTransport().exchange_inv(
                 out, 0)._asdict().items()}
    delivered = {}
    for crc in (True, False):
        wire = chaos.FaultingTransport(LockstepHostTransport(),
                                       cfg.n_replicas, seed=3, crc=crc)
        wire.add("corrupt", 0, 1, 0, 10)
        inb = wire.exchange_inv(out, step=0)
        delivered[crc] = {f: np.asarray(v)[1, 0]
                          for f, v in inb._asdict().items()}
        if crc:
            assert wire.counters["wire_corrupt_dropped"] > 0
        else:
            assert wire.counters["wire_corrupt_applied"] > 0
    for f, v in delivered[True].items():
        assert (v == 0).all(), (
            f"CRC on: corrupted frame must arrive as a DROP (zero block); "
            f"field {f} leaked through")
    assert any(not np.array_equal(delivered[False][f], clean[f])
               for f in clean), (
        "crc=False run should show the scramble reaching the protocol")
    report["crc_red_test"] = True


def check_partition_fast(report: dict) -> None:
    import jax
    import numpy as np

    from hermes_tpu import chaos
    from hermes_tpu.checker import linearizability as lin
    from hermes_tpu.config import HermesConfig, WorkloadConfig
    from hermes_tpu.kvs import KVS
    from hermes_tpu.membership import MembershipService
    from hermes_tpu.obs import Observability

    for backend in ("batched", "sharded"):
        mesh = None
        if backend == "sharded":
            from jax.sharding import Mesh

            mesh = Mesh(np.array(jax.devices()[:5]), ("replica",))
        cfg = HermesConfig(
            n_replicas=5, n_keys=64, n_sessions=4, replay_slots=6,
            value_words=4, ops_per_session=1, lease_steps=5,
            pipeline_depth=2, op_timeout_rounds=6, op_retry_limit=2,
            rebroadcast_every=2, replay_scan_every=4,
            workload=WorkloadConfig(seed=SEED))
        kvs = KVS(cfg, backend=backend, mesh=mesh, record=True)
        obs = kvs.rt.attach_obs(Observability())
        kvs.rt.attach_membership(MembershipService(cfg, confirm_steps=2))
        sched = chaos.Schedule.parse(
            "@4 partition 1 until=60\n@14 freeze 3\n@24 thaw 3\n@62 heal\n")
        runner = chaos.ChaosRunner(kvs, sched)
        futs = []

        def on_step(step):
            if step % 3 == 0 and step < 55:
                r = (step // 3) % cfg.n_replicas
                futs.append(kvs.put(r, (step // 15) % cfg.n_sessions,
                                    (7 * step) % cfg.n_keys, [step + 1]))

        runner.on_step = on_step
        res = runner.run(110, check=True)
        assert res["drained"], f"{backend}: did not drain"
        assert res["checked_ok"], (
            f"{backend}: checker FAIL {res['check_failures']}")
        unresolved = [f for f in futs if not f.done()]
        assert not unresolved, (
            f"{backend}: {len(unresolved)} futures stranded by the adversary")
        mem = [(e.kind, e.replica) for e in kvs.rt.membership.events]
        assert ("remove", 1) in mem and ("join", 1) in mem, (
            f"{backend}: partitioned replica not ejected+rejoined {mem}")
        assert kvs.retried_ops > 0, (
            f"{backend}: no bounded retry fired (stuck={len(kvs.stuck_ops)})")
        ev = [r.get("name") for r in obs.records if r.get("kind") == "event"]
        assert ev.count("membership_fetch") == 0, (
            f"{backend}: detector fetched on the dispatch path")
        committed = [f.result().uid for f in futs
                     if f.result().kind == "put"]
        lost = lin.committed_write_lost(
            committed, kvs.rt.history_ops(), kvs.rt.recorder.aborted_uids)
        assert not lost, (
            f"{backend}: committed-and-observed writes reported "
            f"lost/aborted across partition+heal: {lost}")
        kinds: dict = {}
        for f in futs:
            kinds[f.result().kind] = kinds.get(f.result().kind, 0) + 1
        report[f"partition_{backend}"] = dict(
            ops=len(futs), kinds=kinds, retried=kvs.retried_ops,
            stuck=len(kvs.stuck_ops), committed=len(committed),
            membership=[f"{k}:{r}" for k, r in mem],
            membership_fetches=0, checked_ok=True)


def main() -> int:
    report: dict = {"gate": "netchaos"}
    try:
        check_wire_matrix(report)
        check_determinism(report)
        check_crc_red(report)
        check_partition_fast(report)
    except AssertionError as e:
        report["ok"] = False
        report["error"] = str(e)
        print(json.dumps(report))
        return 1
    report["ok"] = True
    out = os.path.join(os.path.dirname(__file__), "..", "NETCHAOS_SOAK.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
