"""Fused vs split round-sort A/B at the exact bench shape (round-6
tentpole evidence): one process, one chip claim, every cell through
bench.run_mix's measurement protocol — the scripts/arb_compare.py pattern,
with ``over=dict(fused_sort=...)`` as the toggle.

Cells: the primary YCSB-A mix and the contended zipfian mix (deep chains
stress the equal-key-run logic the fusion rewrote), fused on/off.  The
fused cell at mix "a" IS the bench operating point; the cost model
predicts the split cell ~1.3-2.4 ms/round slower (one extra lax.sort).

Writes FUSED_COMPARE.json and prints one JSON line per cell to stderr,
plus a summary line to stdout.  Run on the real chip (default env, no
other TPU process, no timeout-kill).
"""

import json
import os
import sys
import time

sys.path.insert(0, ".")

import bench

CELLS = [
    ("a", {"fused_sort": True}),
    ("a", {"fused_sort": False}),
    ("zipfian", {"fused_sort": True}),
    ("zipfian", {"fused_sort": False}),
]


def main() -> None:
    ok, info = bench.probe_backend(
        float(os.environ.get("HERMES_BENCH_PROBE_TIMEOUT", "180")))
    if not ok:
        print(json.dumps({"error": info}))
        sys.exit(1)

    results = []
    for mix, over in CELLS:
        t0 = time.perf_counter()
        r = bench.run_mix(mix, over=over)
        r["fused_sort"] = over["fused_sort"]
        r["cell_wall_s"] = round(time.perf_counter() - t0, 1)
        results.append(r)
        print(json.dumps(r), file=sys.stderr, flush=True)
        # rewrite after every cell: a mid-matrix chip failure must not
        # discard the completed cells' artifact
        with open("FUSED_COMPARE.json", "w") as f:
            json.dump(results, f, indent=1)

    summary = {}
    for r in results:
        summary.setdefault(r["mix"], {})[
            "fused" if r["fused_sort"] else "split"] = dict(
                writes_per_sec=r["writes_per_sec"], round_us=r["round_us"])
    for mix, cells in summary.items():
        if "fused" in cells and "split" in cells:
            cells["round_ms_saved"] = round(
                (cells["split"]["round_us"] - cells["fused"]["round_us"])
                / 1e3, 2)
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
