#!/bin/bash
# Sanitizer pass over the native components (SURVEY.md §5.2): builds the C++
# TCP transport + checker core together with the standalone harness
# (native/native_test.cpp) under ASan+UBSan and TSan and runs it.  The
# harness runs WITHOUT Python/JAX in the process, so findings belong to our
# code (sanitizing the full python process flags jaxlib internals we don't
# own).
set -euo pipefail
cd "$(dirname "$0")/../hermes_tpu/native"

echo "== ASan + UBSan =="
g++ -O1 -g -fsanitize=address,undefined -fno-omit-frame-pointer \
    -o /tmp/hermes_native_asan native_test.cpp tcp_transport.cpp checker_core.cpp -pthread
/tmp/hermes_native_asan

echo "== TSan (threaded transport) =="
g++ -O1 -g -fsanitize=thread \
    -o /tmp/hermes_native_tsan native_test.cpp tcp_transport.cpp checker_core.cpp -pthread
/tmp/hermes_native_tsan

echo "native sanitizer pass complete"
