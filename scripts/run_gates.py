"""One serial runner for every CI gate (round-11 satellite).

The twelve gates — census, obs-overhead, analysis, pipeline, chaos, elastic,
netchaos, fleet, serving, heap, hostlint, durability — MUST run serially
and never beside a pytest run: the
obs-overhead gate measures per-round wall time against an ablation
baseline and is contention-sensitive (a parallel pytest's CPU load turns a
behavior-identical change into a spurious overhead failure).  That rule
used to live in docs; this runner enforces it in tooling:

  * gates run one at a time, in canonical order, each in its own process
    with the canonical CPU env;
  * a live pytest on the machine aborts the run up front (override with
    --force if you know the contention is harmless, e.g. a collect-only);
  * a gate that overruns its per-gate timeout is KILLED (its whole
    process group — a wedged gate must not stall the serial run or leak
    grandchildren) and recorded as ``timed_out`` in the summary;
  * per-gate wall time and the gate's own JSON report land in ONE summary
    (GATES_SUMMARY.json + one printed JSON line), exit non-zero if any
    gate failed;
  * every gate runs with the crash flight recorder armed
    (``HERMES_FLIGHT_DIR`` -> flight_dumps/): checksummed archives dumped
    during a gate (checker red, stuck op, SIGTERM) are attached to its
    result, and failed gates carry them in the summary's ``gates`` block
    next to the failure they explain.

    python scripts/run_gates.py [--only chaos,netchaos] [--force]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# canonical order: cheap structural gates first, soaks last
GATES = (
    ("census", "check_op_census.py"),
    ("obs-overhead", "check_obs_overhead.py"),
    ("analysis", "check_analysis.py"),
    ("pipeline", "check_pipeline.py"),
    ("chaos", "check_chaos.py"),
    ("elastic", "check_elastic.py"),
    ("netchaos", "check_netchaos.py"),
    ("fleet", "check_fleet.py"),
    ("serving", "check_serving.py"),
    ("heap", "check_heap.py"),
    ("hostlint", "check_hostlint.py"),
    ("durability", "check_durability.py"),
)


def pytest_running() -> list:
    """Best-effort scan for live pytest processes (Linux /proc)."""
    hits = []
    for cmdline in glob.glob("/proc/[0-9]*/cmdline"):
        try:
            with open(cmdline, "rb") as f:
                argv = f.read().split(b"\0")
        except OSError:
            continue
        if any(b"pytest" in a for a in argv):
            hits.append(cmdline.split("/")[2])
    return hits


def gate_env(flight_dir: str) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    # round-18: arm the crash flight recorder in every gate process — on a
    # checker red, a stuck op, or a SIGTERM the obs layer auto-dumps a
    # checksummed archive here (hermes_tpu/obs/flightrec.py), and the
    # summary links the dump next to the failure it explains
    env["HERMES_FLIGHT_DIR"] = flight_dir
    return env


def flight_dumps_in(flight_dir: str) -> set:
    return set(glob.glob(os.path.join(flight_dir, "flight_*.json")))


def run_gate(name: str, script: str, timeout: int, flight_dir: str) -> dict:
    t0 = time.perf_counter()
    dumps_before = flight_dumps_in(flight_dir)
    # own process group: on timeout the WHOLE group is killed, so a gate
    # that wedged inside a grandchild (a spawned replica process, a stuck
    # device claim) cannot stall the serial run or leak orphans
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", script)],
        cwd=REPO, env=gate_env(flight_dir), start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        out_b, err_b = proc.communicate(timeout=timeout)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        out_b, err_b = proc.communicate()
        dumps = sorted(flight_dumps_in(flight_dir) - dumps_before)
        return dict(gate=name, ok=False, rc=-9, timed_out=True,
                    seconds=round(time.perf_counter() - t0, 2),
                    error=f"timed out after {timeout}s (process group "
                          "killed)",
                    stderr_tail=err_b.decode(errors="replace")[-1500:],
                    **({"flight_dumps": dumps} if dumps else {}))
    out = out_b.decode(errors="replace")
    err = err_b.decode(errors="replace")
    secs = round(time.perf_counter() - t0, 2)
    report = None
    for line in reversed(out.strip().splitlines()):
        try:
            report = json.loads(line)
            break
        except json.JSONDecodeError:
            continue
    dumps = sorted(flight_dumps_in(flight_dir) - dumps_before)
    return dict(gate=name, ok=(rc == 0), rc=rc, seconds=secs,
                report=report,
                **({"flight_dumps": dumps} if dumps else {}),
                **({} if rc == 0 else {"stderr_tail": err[-1500:]}))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--only", type=str, default=None,
                    help="comma-separated subset of gate names to run")
    ap.add_argument("--timeout", type=int, default=1200,
                    help="per-gate timeout in seconds")
    ap.add_argument("--force", action="store_true",
                    help="run even while a pytest is live (contention risk:"
                         " the obs-overhead gate may fail spuriously)")
    args = ap.parse_args()

    names = [g[0] for g in GATES]
    only = None
    if args.only:
        only = [s.strip() for s in args.only.split(",") if s.strip()]
        unknown = [s for s in only if s not in names]
        if unknown:
            ap.error(f"unknown gate(s) {unknown}; want a subset of {names}")

    pids = pytest_running()
    if pids and not args.force:
        print(json.dumps(dict(
            ok=False,
            error=f"pytest is running (pid {', '.join(pids)}): the gates "
                  "are contention-sensitive (obs-overhead measures wall "
                  "time) and must never run beside a test suite — wait for "
                  "it or pass --force")))
        return 2

    flight_dir = os.path.join(REPO, "flight_dumps")
    os.makedirs(flight_dir, exist_ok=True)

    results = []
    for name, script in GATES:
        if only is not None and name not in only:
            continue
        print(f"[run_gates] {name} ...", file=sys.stderr, flush=True)
        r = run_gate(name, script, args.timeout, flight_dir)
        print(f"[run_gates] {name}: "
              f"{'ok' if r['ok'] else 'FAIL'} in {r['seconds']}s",
              file=sys.stderr, flush=True)
        results.append(r)

    def _gate_cells(r: dict) -> dict:
        if not isinstance(r.get("report"), dict):
            return {}
        # round-19: the serving gate's columnar-floor cell is a tracked
        # perf number — carry it into the summary's gates block so a
        # regression is visible without digging into the full report
        if r["gate"] == "serving":
            out = {}
            cell = r["report"].get("columnar_floor")
            if isinstance(cell, dict):
                keep = ("ops_per_sec", "required_ops_per_sec",
                        "scalar_baseline_ops_per_sec", "speedup_vs_scalar",
                        "current_scalar_ops_per_sec",
                        "speedup_vs_current_scalar")
                out["columnar_floor"] = {k: cell[k]
                                         for k in keep if k in cell}
            # round-21: the shm leg's one-store floor (>= 2 worker
            # processes feeding ONE store vs the single-process loopback
            # cell) and the replay/kill verdicts are tracked numbers too
            cell = r["report"].get("one_store_floor")
            if isinstance(cell, dict):
                keep = ("ops_per_sec", "loopback_ops_per_sec",
                        "speedup_vs_loopback", "required_speedup",
                        "workers")
                out["one_store_floor"] = {k: cell[k]
                                          for k in keep if k in cell}
            if "shm_replay_identical" in r["report"]:
                out["shm_replay_identical"] = (
                    r["report"]["shm_replay_identical"])
            topo = r["report"].get("one_store_topology")
            if isinstance(topo, dict):
                out["one_store_kill_leg"] = dict(
                    survived=topo.get("kill_survived"),
                    eof=topo.get("kill_eof"))
            return out
        # round-20: the hostlint gate's per-leg timing + verdicts
        if r["gate"] == "hostlint":
            legs = r["report"].get("legs")
            if not isinstance(legs, dict):
                return {}
            return {"legs": {name: dict(ok=leg.get("ok"),
                                        seconds=leg.get("seconds"))
                             for name, leg in legs.items()
                             if isinstance(leg, dict)}}
        # round-22: the durability gate's per-leg verdicts — zero-loss +
        # recovery time per engine, and the measured fsync tax — are
        # tracked cells
        if r["gate"] == "durability":
            out = {}
            for leg in ("kill_batched", "kill_sharded"):
                cell = r["report"].get(leg)
                if isinstance(cell, dict):
                    out[leg] = dict(
                        lost=len(cell.get("committed_write_lost", [])),
                        committed_witnessed=cell.get("committed_witnessed"),
                        recovery_s=cell.get("recovery_s"))
            cell = r["report"].get("wal_overhead")
            if isinstance(cell, dict):
                out["wal_overhead"] = dict(
                    on_vs_off=cell.get("on_vs_off"),
                    wal_on_writes_per_s=(cell.get("wal_on") or {}).get(
                        "writes_per_s"),
                    wal_off_writes_per_s=(cell.get("wal_off") or {}).get(
                        "writes_per_s"))
            return out
        return {}

    summary = dict(
        ok=all(r["ok"] for r in results),
        gates={r["gate"]: dict(ok=r["ok"], seconds=r["seconds"],
                               **({"timed_out": True} if r.get("timed_out")
                                  else {}),
                               **({"flight_dumps": r["flight_dumps"]}
                                  if not r["ok"] and r.get("flight_dumps")
                                  else {}),
                               **_gate_cells(r))
               for r in results},
        total_seconds=round(sum(r["seconds"] for r in results), 2),
        results=results,
    )
    out = os.path.join(REPO, "GATES_SUMMARY.json")
    with open(out, "w") as f:
        json.dump(summary, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps(dict(ok=summary["ok"], gates=summary["gates"],
                          total_seconds=summary["total_seconds"])))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
