"""Full-scale acceptance artifact (VERDICT round-1 item 4).

Runs all five BASELINE acceptance configs (BASELINE.json:7-11) at
``--scale 1.0`` — the real 1M-key shape — on the available chip, with the
columnar recorder + native witness checker gating every run, and writes
``ACCEPTANCE_FULL.json`` with counters, verdicts, and wall times.

    python scripts/full_acceptance.py [--scale 1.0] [--max-steps 20000]

Config 3 (Zipfian-0.99 hotspot) is the long pole by design: hot-key writes
serialize at n_replicas per round (BASELINE.md "Zipfian note"), so draining
S*G ops per session through contended keys takes thousands of rounds.
"""

import argparse
import json
import sys
import time

sys.path.insert(0, ".")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--max-steps", type=int, default=20000)
    ap.add_argument("--out", default="ACCEPTANCE_FULL.json")
    ap.add_argument("--configs", default="1,2,2r,3,3c,4,5,s",
                    help="comma list of 1..5, '2r' (config 2 under RMW "
                         "retry-in-place), '3c' (config 3 under the "
                         "sort+chain hot-key mitigation) and 's' (the "
                         "sparse-key client-KVS variant of config 1)")
    ap.add_argument("--check-keys", type=int, default=0,
                    help="sample size for the checker; 0 = EVERY touched "
                         "key (the artifact default)")
    args = ap.parse_args()

    import jax

    from hermes_tpu import acceptance

    toks = [x.strip() for x in args.configs.split(",")]
    bad = [x for x in toks if x not in ("1", "2", "2r", "3", "3c", "4", "5", "s")]
    if bad:  # reject upfront — never discard hours of completed runs
        ap.error(f"--configs tokens must be 1..5, '2r', '3c' or 's'; got {bad}")

    results = {}
    for tok in toks:
        t0 = time.perf_counter()
        if tok == "s":
            counters, verdict = acceptance.run_sparse_variant(
                scale=args.scale, max_steps=args.max_steps,
                check_keys=args.check_keys or None,
                log=lambda s: print(f"  {s}", file=sys.stderr),
            )
        else:
            counters, verdict = acceptance.run_config(
                tok if tok in ("2r", "3c") else int(tok),
                scale=args.scale, max_steps=args.max_steps,
                check_keys=args.check_keys or None,
                log=lambda s: print(f"  {s}", file=sys.stderr),
            )
        wall = time.perf_counter() - t0
        entry = {"counters": counters, "wall_s": round(wall, 1)}
        entry.update(verdict.to_dict() if verdict else {
            "verdict_ok": None, "keys_checked": None,
            "failures": [], "undecided": [],
        })
        results[tok] = entry
        print(f"config {tok}: ok={entry['verdict_ok']} drained="
              f"{counters.get('drained')} wall={wall:.1f}s "
              f"{ {k: v for k, v in counters.items() if k.startswith('n_')} }",
              file=sys.stderr)

    out = {
        "scale": args.scale,
        "platform": jax.devices()[0].platform,
        "device": getattr(jax.devices()[0], "device_kind", "?"),
        "results": results,
        "all_ok": all(r["verdict_ok"] and r["counters"].get("drained")
                      for r in results.values()),
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"acceptance_all_ok": out["all_ok"]}))


if __name__ == "__main__":
    main()
