"""Round-14 serving gate (CI, the NINTH gate): overload is a first-class,
seeded, gated failure mode — the front-end must shed loudly, honor
deadlines, and degrade gracefully rather than wedge.

Seven legs, CPU-smoke sized (joins the earlier gates in
scripts/run_gates.py — gates run SERIALLY, never beside pytest):

  1. overload soak, both engines — an open-loop Poisson soak at >= 2x
     the MEASURED closed-loop capacity (the capacity probe runs first,
     through the same serving path) over the batched AND sharded KVS at
     pipeline depth 2 must (a) keep the linearizability checker green
     with committed_write_lost == [] (no client-visible commit
     contradicted), (b) resolve EVERY request loudly — admitted ops as
     committed/deadline/rejected, refused ops as RETRY_AFTER; response
     conservation + per-tenant admission accounting exactness are
     asserted by verify_serving — and (c) bound admitted-op p99 by the
     configured deadline (+ one virtual round: deadline enforcement is
     checked once per pump);
  2. deterministic replay — the same seed + configs replay the soak to
     a byte-identical response log (sha256 over the emitted response
     bytes, the chaos-schedule determinism contract applied to load);
  3. fleet facade — the same envelope over a 2-group Fleet: the soak
     spans both groups, every group's checker is green, verify_fleet
     holds, and the serving invariants pass through the router;
  4. seeded overload storm — chaos ``overload x=N`` windows (Schedule.
     overload_storm attached to the arrival shaper via ChaosRunner's
     load= seam) burst the arrival rate mid-soak; the envelope must
     still satisfy (b)+(c), shed visibly (retry_after > 0), and the
     executed chaos log + response log must replay byte-identically;
  5. round-16 read soak — a YCSB-B mix with K_MGET batches riding every
     8th arrival at >= 2x capacity must resolve every request loudly,
     keep the checker green with ``stale_read == []`` (local reads
     verified against the write history), and rung 2 must keep ALL-hot
     batched reads serving while a batch carrying one cold key (and any
     scan) sheds R_SHED_READ;
  6. round-19 columnar plane — the columnar soak satisfies the same
     envelope (loud, checker green, committed_write_lost == [],
     byte-identical replay) AND the loopback columnar path sustains the
     serving-throughput FLOOR: >= 50x the PR-10 scalar closed-loop
     baseline cell recorded in BENCH_LATENCY.json, cell-vs-cell on this
     host (the floor cell is carried into GATES_SUMMARY.json by
     run_gates.py);
  7. round-21 shm IPC plane — (a) the deterministic one-store soak over
     REAL shm rings, offered 2x the rings' total slot capacity so the
     backpressure path must cycle every slot: conservation exact across
     the ring boundary (verify_columnar), checker green,
     committed_write_lost == [] against the client-visible uid set,
     and a byte-identical per-worker response-log replay; (b) the REAL
     multi-process topology — 2 worker processes sharding accepts on
     one port feeding ONE store, every batched request answered loudly,
     frontend conservation exact, then kill -9 of one worker mid-run:
     the store and the surviving worker keep serving, the dead worker's
     clients see EOF (never a hang); (c) the recorded one_store floor —
     BENCH_LATENCY.json's one_store_workers_2 cell must sustain >= 2x
     the columnar_loopback cell, cell-vs-cell, with honest topology
     labels (one_store cells carried into GATES_SUMMARY.json by
     run_gates.py).

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/check_serving.py

Prints one JSON line (also written to SERVING_SOAK.json); exit non-zero
on any violation.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

SEED = 14
# tight enough that a 2x-capacity soak's tail CROSSES it (the deadline
# machinery must fire, not just exist), loose enough that the bulk commits
DEADLINE_US = 8_000
ROUND_US = 1000


def _cfg(n_replicas=4, **over):
    from hermes_tpu.config import HermesConfig, WorkloadConfig

    kw = dict(
        n_replicas=n_replicas, n_keys=64, n_sessions=4, replay_slots=6,
        ops_per_session=96, value_words=6, replay_age=6,
        replay_scan_every=4, rebroadcast_every=2, lease_steps=6,
        pipeline_depth=2, op_timeout_rounds=48,
        workload=WorkloadConfig(read_frac=0.5, seed=SEED),
    )
    kw.update(over)
    return HermesConfig(**kw)


def _scfg(**over):
    from hermes_tpu.serving import ServingConfig

    kw = dict(tenant_rate_per_s=200_000.0, tenant_burst=64.0,
              tenant_quota=12, queue_cap=48, round_us=ROUND_US,
              shed_write_frac=0.6, shed_read_frac=0.9)
    kw.update(over)
    return ServingConfig(**kw)


def _store(backend: str, record=True):
    from hermes_tpu.kvs import KVS

    if backend == "sharded":
        import jax
        import numpy as np
        from jax.sharding import Mesh

        mesh = Mesh(np.array(jax.devices()[:4]), ("replica",))
        return KVS(_cfg(), backend="sharded", mesh=mesh,
                   record="array" if record else False)
    return KVS(_cfg(), record="array" if record else False)


def _assert_envelope(res: dict, report_key: str, report: dict,
                     require_shed: bool = True,
                     require_deadline: bool = False) -> None:
    """(b) + (c): every op resolved loudly, tail bounded by the deadline."""
    st = res["statuses"]
    resolved = (res["completed"] + res["deadline"] + st.get("rejected", 0)
                + res["lost"] + st.get("retry_after", 0))
    assert res["ops_offered"] == res["sent"], res
    assert resolved == res["sent"], (
        f"{report_key}: {res['sent']} requests but only {resolved} loud "
        f"resolutions ({st})")
    assert res["lost"] == 0, f"{report_key}: clean soak lost ops ({st})"
    if require_shed:
        assert st.get("retry_after", 0) > 0, (
            f"{report_key}: a >=2x-capacity soak shed nothing — the "
            f"admission path is not engaging ({st})")
    if require_deadline:
        assert res["deadline"] > 0, (
            f"{report_key}: the overload tail never crossed the "
            f"{DEADLINE_US}us deadline — the enforcement path did not "
            f"fire ({st})")
    bound = DEADLINE_US + ROUND_US
    assert res["p99_latency_us"] is not None \
        and res["p99_latency_us"] <= bound, (
        f"{report_key}: admitted-op p99 {res['p99_latency_us']}us exceeds "
        f"the deadline bound {bound}us")
    report[report_key] = {k: v for k, v in res.items()
                          if not k.startswith("_")}


def _check_history(store, res) -> None:
    from hermes_tpu.checker import linearizability as lin
    from hermes_tpu.serving.soak import committed_uids

    v = store.rt.check()
    assert v.ok, f"checker FAIL: {[f.reason[:160] for f in v.failures[:2]]}"
    uids = committed_uids(res["_frontend"], res["_server"])
    assert uids, "soak committed nothing the client saw"
    lost = lin.committed_write_lost(uids, store.rt.history_ops(),
                                    store.rt.recorder.aborted_uids)
    assert not lost, (
        f"committed-and-observed writes contradicted by the history: "
        f"{lost[:4]}")


def check_engines(report: dict) -> None:
    from hermes_tpu.serving import measure_capacity, run_open_loop
    from hermes_tpu.workload.openloop import MixSpec

    spec = MixSpec(name="uniform", tenants=4)
    for backend in ("batched", "sharded"):
        cap = measure_capacity(_store(backend, record=False), _scfg(),
                               spec, n=240, seed=SEED)
        rate = 2.0 * cap["ops_per_vs"]
        shas = []
        for rep in range(2):
            store = _store(backend)
            res = run_open_loop(store, _scfg(), spec, rate_per_s=rate,
                                n=500, seed=SEED, deadline_us=DEADLINE_US)
            if rep == 0:
                _assert_envelope(res, f"{backend}_soak", report,
                                 require_deadline=True)
                _check_history(store, res)
                report[f"{backend}_soak"]["capacity_probe"] = cap
                report[f"{backend}_soak"]["rate_per_vs"] = rate
            shas.append(res["response_log_sha"])
        assert shas[0] == shas[1], (
            f"{backend}: same seed replayed to a DIFFERENT response log "
            f"({shas})")
        report[f"{backend}_replay_identical"] = True


def check_fleet(report: dict) -> None:
    from hermes_tpu.config import FleetConfig
    from hermes_tpu.fleet import Fleet, verify_fleet
    from hermes_tpu.serving import measure_capacity, run_open_loop
    from hermes_tpu.workload.openloop import MixSpec

    spec = MixSpec(name="uniform", tenants=4)
    fcfg = FleetConfig(groups=2, base=_cfg())
    cap = measure_capacity(Fleet(fcfg), _scfg(), spec, n=240, seed=SEED)
    rate = 2.0 * cap["ops_per_vs"]
    fleet = Fleet(fcfg, record="array")
    res = run_open_loop(fleet, _scfg(), spec, rate_per_s=rate, n=500,
                        seed=SEED, deadline_us=DEADLINE_US)
    _assert_envelope(res, "fleet_soak", report)
    # the mix must actually span both groups
    import numpy as np

    from hermes_tpu.workload.openloop import make_mix

    fe = res["_frontend"]
    mix = make_mix(spec, fe.n_keys, 500, SEED, value_words=fe.u)
    gids, _ = fleet.router.locate(np.asarray(mix["key"], np.int64))
    assert set(np.asarray(gids).tolist()) == {0, 1}, "mix spanned one group"
    verdicts = fleet.check()
    assert verdicts["ok"], f"fleet checker FAIL {verdicts}"
    verify_fleet(fleet)
    # the client-visible-commit invariant THROUGH the router: every uid
    # the client saw commit must be a definite committed write in some
    # group's history and aborted in none (the engines-leg cross-check
    # applied to the fleet facade)
    from hermes_tpu.checker import linearizability as lin
    from hermes_tpu.serving.soak import committed_uids

    uids = committed_uids(res["_frontend"], res["_server"])
    assert uids, "fleet soak committed nothing the client saw"
    all_ops = [o for g in fleet.groups for o in g.rt.history_ops()]
    aborted = set()
    for g in fleet.groups:
        aborted |= set(g.rt.recorder.aborted_uids)
    lost = lin.committed_write_lost(uids, all_ops, aborted)
    assert not lost, (
        f"fleet: committed-and-observed writes contradicted by the "
        f"group histories: {lost[:4]}")
    report["fleet_soak"]["group_verdicts"] = verdicts["groups"]
    report["fleet_soak"]["capacity_probe"] = cap


def check_overload_storm(report: dict) -> None:
    from hermes_tpu import chaos
    from hermes_tpu.serving import run_open_loop
    from hermes_tpu.workload.openloop import (MixSpec, ShapedArrivals,
                                              hot_set)

    # a REAL hot-key mix with the hot set handed to the shed ladder, so
    # rung-2 retention is exercised through the storm, not only in units
    spec = MixSpec(name="hotkey", distribution="hotkey", hot_frac=0.8,
                   hot_keys=4, tenants=4)
    scfg = _scfg(hot_keys=hot_set(spec))
    sched = chaos.Schedule.overload_storm(SEED, steps=400, n_windows=2,
                                          x_range=(3.0, 6.0))
    assert len(sched) == 2
    outs = []
    for _ in range(2):
        store = _store("batched")
        arrivals = ShapedArrivals(1200.0, 400, SEED)
        runner = chaos.ChaosRunner(store, chaos.Schedule(list(sched)),
                                   load=arrivals)
        res = run_open_loop(store, scfg, spec, rate_per_s=1200.0,
                            n=400, seed=SEED, deadline_us=DEADLINE_US,
                            chaos_runner=runner, arrivals=arrivals)
        outs.append((res, runner.log_json()))
    res = outs[0][0]
    _assert_envelope(res, "overload_storm", report, require_shed=False)
    applied = [e for e in json.loads(outs[0][1]) if e["kind"] == "overload"]
    assert applied, "no overload window applied"
    assert outs[0][1] == outs[1][1], "executed chaos logs differ"
    assert outs[0][0]["response_log_sha"] == outs[1][0]["response_log_sha"], \
        "overload-storm response logs differ across replays"
    report["overload_storm"]["windows_applied"] = applied
    report["overload_storm_replay_identical"] = True


def check_read_soak(report: dict) -> None:
    """Round-16 read leg: the K_MGET/K_SCAN serving path under 2x
    overload must (a) resolve every request loudly with the envelope
    invariants intact, (b) keep the linearizability checker green with
    stale_read == [] (local reads are VERIFIED against the write
    history), and (c) keep rung-2 hot-key reads serving while non-hot
    batched reads shed — the ladder's read semantics applied to the
    batched verbs."""
    from hermes_tpu.checker import linearizability as lin
    from hermes_tpu.serving import (LoopbackServer, Frontend, ServingConfig,
                                    VirtualClock, measure_capacity,
                                    verify_serving, wire)
    from hermes_tpu.workload.openloop import MixSpec, hot_set, make_mix
    from hermes_tpu.workload.ycsb import READ_MIXES
    from hermes_tpu.serving.soak import committed_uids
    import numpy as np

    spec = MixSpec(name="ycsb_b", tenants=4, **READ_MIXES["b"])
    cap = measure_capacity(_store("batched", record=False), _scfg(), spec,
                           n=240, seed=SEED)
    rate = 2.0 * cap["ops_per_vs"]
    store = _store("batched")
    clock = VirtualClock()
    fe = Frontend(store, _scfg(), clock=clock)
    lb = LoopbackServer(fe)
    n = 400
    mix = make_mix(spec, fe.n_keys, n, SEED, value_words=fe.u)
    from hermes_tpu.workload.openloop import ShapedArrivals

    arrivals = ShapedArrivals(rate, n, SEED)
    round_s = ROUND_US * 1e-6
    sent = mgets = 0
    rounds = 0
    while rounds < 200_000:
        due = arrivals.due(clock.t)
        for _ in range(due):
            if sent >= n:
                break
            i = sent
            sent += 1
            if i % 8 == 7:
                # every 8th arrival is a BATCHED read: 8 mix keys
                # through K_MGET (the round-16 verb under overload)
                ks = [int(k) for k in mix["key"][max(0, i - 8): i]]
                lb.submit(wire.ReadRequest(
                    kind="mget", req_id=i + 1,
                    tenant=int(mix["tenant"][i]), keys=ks or [0],
                    deadline_us=DEADLINE_US))
                mgets += 1
            else:
                lb.submit(wire.Request(
                    kind=("get", "put", "rmw")[int(mix["kind"][i])],
                    req_id=i + 1, tenant=int(mix["tenant"][i]),
                    key=int(mix["key"][i]), deadline_us=DEADLINE_US,
                    value=mix["value"][i].tolist()))
        lb.pump()
        clock.advance(round_s)
        rounds += 1
        if sent >= n and not (fe._intake or fe._pending or fe._abandoned):
            break
    lb.drain()
    ev = verify_serving(fe)
    assert mgets > 10, "read soak drove no batched reads"
    v = store.rt.check()
    assert v.ok, (
        f"read soak checker FAIL: "
        f"{[f.reason[:160] for f in v.failures[:2]]}")
    stale = lin.stale_read(store.rt.history_ops())
    assert not stale, f"read soak produced STALE reads: {stale[:3]}"
    uids = committed_uids(fe, lb)
    lost = lin.committed_write_lost(uids, store.rt.history_ops(),
                                    store.rt.recorder.aborted_uids)
    assert not lost, f"read soak contradicted committed writes: {lost[:3]}"
    report["read_soak"] = dict(
        capacity_probe=cap, rate_per_vs=rate, mget_requests=mgets,
        read_stats=store.read_stats(), **ev)

    # rung-2 retention through the BATCHED verbs: with the queue jammed
    # past shed_read_frac, an all-hot mget still serves while a batch
    # carrying one cold key sheds (R_SHED_READ) — a batch cannot smuggle
    # cold keys behind a hot one
    spec2 = MixSpec(name="hotkey", distribution="hotkey", hot_keys=4)
    scfg2 = _scfg(hot_keys=hot_set(spec2), queue_cap=16,
                  shed_write_frac=0.3, shed_read_frac=0.5, tenant_quota=32)
    store2 = _store("batched", record=False)
    clock2 = VirtualClock()
    fe2 = Frontend(store2, scfg2, clock=clock2)
    lb2 = LoopbackServer(fe2)
    # jam the intake queue past the rung-2 watermark without pumping
    # (hot-key gets — they admit at every rung, so the jam can build)
    for i in range(int(scfg2.queue_cap * scfg2.shed_read_frac) + 2):
        r = lb2.submit(wire.Request(kind="get", req_id=1000 + i, tenant=0,
                                    key=i % 4))
        assert r is None, "queue jam refused too early"
    hot_rsp = lb2.submit(wire.ReadRequest(kind="mget", req_id=1, tenant=1,
                                          keys=[0, 1, 2, 3]))
    cold_rsp = lb2.submit(wire.ReadRequest(kind="mget", req_id=2, tenant=1,
                                           keys=[0, 1, 2, 40]))
    scan_rsp = lb2.submit(wire.ReadRequest(kind="scan", req_id=3, tenant=1,
                                           lo=0, hi=32))
    assert hot_rsp is None, "rung 2 shed an ALL-HOT batched read"
    assert cold_rsp is not None \
        and cold_rsp.status == wire.S_RETRY_AFTER \
        and cold_rsp.reason == wire.R_SHED_READ, cold_rsp
    assert scan_rsp is not None \
        and scan_rsp.status == wire.S_RETRY_AFTER, scan_rsp
    lb2.drain()
    verify_serving(fe2)
    report["read_rung2"] = dict(hot_admitted=True, cold_shed=True,
                                scan_shed=True)


def check_columnar(report: dict) -> None:
    """Round-19 columnar leg: (a) the columnar soak at >= 2x capacity
    satisfies the same envelope — every request loud, checker green,
    committed_write_lost == [], replay byte-identical; (b) the
    serving-throughput FLOOR — the loopback columnar path must sustain
    >= 50x the PR-10 scalar closed-loop baseline cell recorded in
    BENCH_LATENCY.json on this host (cell-vs-cell)."""
    from hermes_tpu.serving import measure_capacity
    from hermes_tpu.serving.soak import (measure_columnar_floor,
                                         run_columnar_soak)
    from hermes_tpu.workload.openloop import MixSpec

    spec = MixSpec(name="uniform", tenants=4)
    cap = measure_capacity(_store("batched", record=False), _scfg(), spec,
                           n=240, seed=SEED)
    rate = 2.0 * cap["ops_per_vs"]
    shas = []
    for rep in range(2):
        store = _store("batched")
        res = run_columnar_soak(store, _scfg(), spec, rate_per_s=rate,
                                n=500, seed=SEED, deadline_us=DEADLINE_US)
        if rep == 0:
            # the columnar plane drains the same 2x-capacity offered
            # load fast enough that nothing lingers past the deadline —
            # shed must still engage (refusals loud); the deadline
            # machinery gets its own constrained-store leg below
            _assert_envelope(res, "columnar_soak", report)
            _check_history(store, res)
            report["columnar_soak"]["capacity_probe"] = cap
            report["columnar_soak"]["rate_per_vs"] = rate
        shas.append(res["response_log_sha"])
    assert shas[0] == shas[1], (
        f"columnar: same seed replayed to a DIFFERENT response log "
        f"({shas})")
    report["columnar_replay_identical"] = True

    # columnar DEADLINE enforcement: throttle the store to one op in
    # flight so intake backs up past the deadline — expiries must fire
    # (intake-side S_DEADLINE) while the rest commit, envelope intact
    res = run_columnar_soak(
        _store("batched", record=False),
        _scfg(store_inflight_cap=1, tenant_quota=64, queue_cap=256),
        spec, rate_per_s=rate, n=300, seed=SEED,
        deadline_us=DEADLINE_US)
    _assert_envelope(res, "columnar_deadline_soak", report,
                     require_shed=False, require_deadline=True)

    # (b) the throughput floor.  The bar is PINNED to the PR-10 scalar
    # closed-loop cell (the ~350 ops/s figure the round-19 gap was
    # measured against, as recorded in BENCH_LATENCY.json before this
    # round).  It is deliberately NOT re-read from the live artifact:
    # the round-19 pump-lock fix sped the scalar path itself ~10x, and
    # re-basing the 50x floor on the improved scalar cell would turn a
    # fixed acceptance bar into a moving target.  The live scalar cell
    # is still read and reported beside the pinned one for honesty.
    baseline = 351.8  # PR-10 scalar closed-loop cell (pinned)
    baseline_src = "pr10_recorded_cell"
    current_scalar = None
    bench_path = os.path.join(os.path.dirname(__file__), "..",
                              "BENCH_LATENCY.json")
    if os.path.exists(bench_path):
        with open(bench_path) as f:
            cells = json.load(f).get("cells", {})
        cell = cells.get("throughput", {})
        if cell.get("ops_per_sec") and not cell.get("error"):
            current_scalar = float(cell["ops_per_sec"])
    floor = 50.0 * baseline
    fl = measure_columnar_floor()
    assert fl["retried"] == 0 or fl["retried"] < fl["ops"], fl
    assert fl["ops_per_sec"] >= floor, (
        f"columnar floor MISSED: {fl['ops_per_sec']} ops/s < 50x scalar "
        f"baseline {baseline} ({floor:.0f}) [{baseline_src}] — {fl}")
    report["columnar_floor"] = dict(
        **fl, scalar_baseline_ops_per_sec=baseline,
        baseline_source=baseline_src, required_ops_per_sec=round(floor, 1),
        speedup_vs_scalar=round(fl["ops_per_sec"] / baseline, 1))
    if current_scalar is not None:
        report["columnar_floor"]["current_scalar_ops_per_sec"] = (
            current_scalar)
        report["columnar_floor"]["speedup_vs_current_scalar"] = round(
            fl["ops_per_sec"] / current_scalar, 1)


def check_shm(report: dict) -> None:
    """Round-21 shm leg (docstring item 7): the one-store IPC plane —
    ring-soak conservation + replay, the real-process topology with a
    kill -9 sub-leg, and the recorded one_store throughput floor."""
    import numpy as np

    from hermes_tpu.checker import linearizability as lin
    from hermes_tpu.config import HermesConfig
    from hermes_tpu.kvs import KVS
    from hermes_tpu.serving import wire
    from hermes_tpu.serving.ipc import OneStoreServer, run_shm_soak

    # (a) deterministic soak over REAL shm rings: 2 workers, 4 slots of
    # 64 rows each per ring (256-row capacity), 512 ops per worker — 2x
    # the ring capacity, so every slot is claimed, committed, polled and
    # acked at least twice and the ring-full skip path must engage
    kw = dict(n_workers=2, ops_per_worker=512, batch=64, nslots=4,
              seed=SEED)
    runs = [run_shm_soak(**kw) for _ in range(2)]
    a = runs[0]
    assert a["ok"] and a["checker_ok"], a
    assert a["worker_log_sha"] == runs[1]["worker_log_sha"], (
        "shm soak replayed to DIFFERENT per-worker response logs "
        f"({a['worker_log_sha']} vs {runs[1]['worker_log_sha']})")
    assert a["ipc"] == runs[1]["ipc"] \
        and a["verify"] == runs[1]["verify"], (
        "shm soak counters differ across replays")
    ipc, ver = a["ipc"], a["verify"]
    assert ipc["rows_in"] == ipc["rows_out"] == 1024, ipc
    assert ipc["torn_slots"] == 0 and ipc["dead_drop_rows"] == 0, ipc
    assert ipc["dead_workers"] == [], ipc
    # conservation across the ring boundary: every row in is a row out,
    # every request the frontend accepted is resolved, nothing lost
    assert ver["requests"] == ver["responses"] == 1024, ver
    assert ver["lost"] == 0, ver
    assert a["_client_uids"], "shm soak committed nothing the client saw"
    store = a["_store"]
    lost = lin.committed_write_lost(a["_client_uids"],
                                    store.rt.history_ops(),
                                    store.rt.recorder.aborted_uids)
    assert not lost, (
        f"shm soak: committed-and-observed writes contradicted by the "
        f"history: {lost[:4]}")
    report["shm_soak"] = {k: v for k, v in a.items()
                          if not k.startswith("_")}
    report["shm_replay_identical"] = True

    # (b) the REAL topology: 2 shm worker processes sharding accepts on
    # one SO_REUSEPORT port, all feeding ONE store.  4 clients push 4096
    # rows total — 2x the rings' combined 2048-row slot capacity — then
    # worker 0 is SIGKILLed and the survivors must keep answering while
    # the dead worker's clients see EOF, loudly, never a hang.
    import os as _os
    import signal
    import time

    def _shm_batch(cl, u, n_keys, rng, tenant, k=64):
        kind = np.where(rng.random(k) < 0.5, wire.K_GET,
                        wire.K_PUT).astype(np.uint8)
        return wire.ReqBatch(
            kind=kind, req_id=cl.next_ids(k),
            tenant=np.full(k, tenant, np.uint16),
            trace=np.zeros(k, np.uint16),
            deadline_us=np.zeros(k, np.uint32),
            key=rng.integers(0, n_keys, k).astype(np.int64),
            value=rng.integers(0, 99, (k, u)).astype(np.int32))

    from hermes_tpu.serving.rpc import ColumnarClient

    cfg = HermesConfig(n_replicas=4, n_keys=1 << 10, n_sessions=64,
                       value_words=6)
    scfg = _scfg(tenant_rate_per_s=1e9, tenant_burst=1e9,
                 tenant_quota=1 << 20, queue_cap=4096)
    store = KVS(cfg)
    srv = OneStoreServer(store, scfg, n_workers=2, nslots=8,
                         slot_rows=128)
    rng = np.random.default_rng(SEED)
    answered = retried = 0
    try:
        assert srv.alive() == 2, "one-store server booted short"
        clients = [ColumnarClient(srv.addr, srv.fe.u) for _ in range(4)]
        for _ in range(16):  # 4 clients x 16 batches x 64 = 4096 rows
            for ci, cl in enumerate(clients):
                out = cl.call_batch(
                    _shm_batch(cl, srv.fe.u, cfg.n_keys, rng, ci))
                assert len(out) == 64, "one-store round trip dropped rows"
                for r in out.values():
                    assert r.status in (wire.S_OK, wire.S_RETRY_AFTER), r
                    answered += 1
                    retried += r.status == wire.S_RETRY_AFTER
        # the kill sub-leg
        _os.kill(srv.procs[0].pid, signal.SIGKILL)
        srv.procs[0].join(5)
        assert srv.alive() == 1, "SIGKILL left the worker alive"
        time.sleep(0.5)
        survived = eof = 0
        for ci, cl in enumerate(clients):
            try:
                out = cl.call_batch(
                    _shm_batch(cl, srv.fe.u, cfg.n_keys, rng, ci))
                assert len(out) == 64
                survived += 1
            except (ConnectionError, OSError):
                eof += 1
        assert survived >= 1 and survived + eof == 4, (
            f"worker kill: {survived} survived + {eof} EOF != 4 clients")
        assert srv.pump_error is None, srv.pump_error
        for cl in clients:
            cl.close()
    finally:
        srv.close()
    assert srv.owner.dead[0] and not srv.owner.dead[1], (
        "owner did not tombstone exactly the killed worker")
    assert srv.fe.requests == srv.fe.responses, (
        f"one-store conservation broke across the worker kill: "
        f"{srv.fe.requests} requests vs {srv.fe.responses} responses")
    report["one_store_topology"] = dict(
        workers=2, clients=4, rows_answered=answered,
        retry_after=int(retried), kill_survived=survived, kill_eof=eof,
        ipc=srv.owner.counters(),
        requests=srv.fe.requests, responses=srv.fe.responses)

    # (c) the recorded one_store floor, cell-vs-cell on this host: >= 2
    # worker processes feeding ONE store must sustain >= 2x the single-
    # process columnar_loopback cell, with honest topology labels
    bench_path = os.path.join(os.path.dirname(__file__), "..",
                              "BENCH_LATENCY.json")
    assert os.path.exists(bench_path), (
        "BENCH_LATENCY.json missing — run `python bench.py --serve` "
        "to record the one_store floor cell")
    with open(bench_path) as f:
        cells = json.load(f).get("cells", {})
    lb_cell = cells.get("columnar_loopback", {})
    os_cell = cells.get("one_store_workers_2", {})
    assert lb_cell.get("ops_per_sec") and not lb_cell.get("error"), lb_cell
    assert os_cell.get("ops_per_sec") and not os_cell.get("error"), (
        f"one_store_workers_2 cell missing or error-carrying: {os_cell}")
    assert os_cell.get("topology") == "one-store", os_cell
    for w_cell in ("columnar_workers_2", "columnar_workers_4"):
        c = cells.get(w_cell)
        if isinstance(c, dict) and not c.get("error"):
            assert c.get("topology") == "private-store-per-worker", (
                f"{w_cell} lost its honesty label: {c}")
    ratio = float(os_cell["ops_per_sec"]) / float(lb_cell["ops_per_sec"])
    assert ratio >= 2.0, (
        f"one_store floor MISSED: one_store_workers_2 "
        f"{os_cell['ops_per_sec']} ops/s is only {ratio:.2f}x the "
        f"columnar_loopback cell {lb_cell['ops_per_sec']} (need >= 2x)")
    report["one_store_floor"] = dict(
        ops_per_sec=os_cell["ops_per_sec"],
        loopback_ops_per_sec=lb_cell["ops_per_sec"],
        speedup_vs_loopback=round(ratio, 2),
        required_speedup=2.0, workers=os_cell.get("workers"),
        statuses=os_cell.get("statuses"))


def main() -> int:
    report: dict = {"gate": "serving"}
    try:
        check_engines(report)
        check_fleet(report)
        check_overload_storm(report)
        check_read_soak(report)
        check_columnar(report)
        check_shm(report)
    except AssertionError as e:
        report["ok"] = False
        report["error"] = str(e)
        print(json.dumps(report, default=str))
        return 1
    report["ok"] = True
    out = os.path.join(os.path.dirname(__file__), "..", "SERVING_SOAK.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True, default=str)
        f.write("\n")
    print(json.dumps(report, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
