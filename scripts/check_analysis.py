"""Static-analysis findings gate (CI): the jaxpr invariant analyzer
(hermes_tpu/analysis) must report no NEW error/warn findings on the fast
engines, at the default and bench configs, batched + sharded, fused +
split sort — and, since ISSUE 8, on the standalone kernel matrix (every
in-tree Pallas kernel through the sub-interpreter), with the
differential sanitizer (analysis/diffcheck.py) cross-checking the
abstract kernel cells against seeded concrete interpret-mode runs.
Per-cell wall time rides the JSON line into GATES_SUMMARY.json.

Why a gate: the engines' packed int32 words (timestamps, INV headers, the
fused sort key) are protocol invariants that a refactor can silently
alias — one widened field or one un-audited set-scatter corrupts
arbitration with no runtime error until the linearizability checker
trips over a mangled history.  The analyzer proves the packing at trace
time; this script polices it the same measure-then-gate way as
scripts/check_op_census.py and scripts/check_obs_overhead.py.

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/check_analysis.py [--update] [--out FINDINGS_JSONL]

ANALYSIS_BASELINE.json grandfathers known findings (keyed stably without
line numbers); ``--update`` rewrites it after an INTENTIONAL change so
the diff shows up in review.  Exit non-zero on any finding not in the
baseline.  Info-severity findings (audited assumptions) never gate but
are counted, so a silently growing assumption surface is visible in the
JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")


def gate_configs() -> dict:
    """The analyzed matrix: named configs -> HermesConfig.  Default (race
    arbiter) + the bench operating shape (sort+chain+fused — the split
    program is added automatically as the A/B variant)."""
    import dataclasses

    from hermes_tpu.config import HermesConfig

    import bench

    return {
        "default": HermesConfig(),
        "bench": bench._cfg("a"),
        "bench-rmw": bench._cfg("rmw"),
        # round-15: the mega path's kernels analyzed INSIDE the round
        # programs (the split A/B variant is added automatically)
        "bench-mega": dataclasses.replace(bench._cfg("a"),
                                          mega_round=True),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--baseline", default="ANALYSIS_BASELINE.json")
    ap.add_argument("--update", action="store_true",
                    help="rewrite the baseline's grandfathered findings "
                    "instead of failing on drift")
    ap.add_argument("--out", default=None, metavar="FINDINGS_JSONL",
                    help="also export every finding as obs-schema JSONL")
    ap.add_argument("--configs", default=None,
                    help="comma-separated subset of the gate configs")
    ap.add_argument("--no-kernels", action="store_true",
                    help="skip the standalone kernel matrix + sanitizer")
    args = ap.parse_args()

    from hermes_tpu import analysis as ana

    names = gate_configs()
    if args.configs:
        want = args.configs.split(",")
        unknown = [w for w in want if w not in names]
        if unknown:
            # a typo must not turn into a vacuous green gate
            print(f"unknown gate config(s) {unknown}; have {sorted(names)}",
                  file=sys.stderr)
            return 2
        names = {k: names[k] for k in want}

    measured: dict = {}
    all_reports = []
    n_err = n_warn = n_info = 0
    for cname, cfg in names.items():
        print(f"analyzing {cname} (S={cfg.n_sessions}, K={cfg.n_keys}, "
              f"arb={cfg.arb_mode}, fused={cfg.use_fused_sort})...",
              file=sys.stderr)
        reports = ana.analyze_config(cfg)
        for r in reports:
            for f in r["findings"]:
                f.engine = f"{cname}:{f.engine}"
                if f.severity == ana.ERROR:
                    n_err += f.count
                elif f.severity == ana.WARN:
                    n_warn += f.count
                else:
                    n_info += f.count
        for k, v in ana.key_counts(ana.findings_of(reports)).items():
            measured[k] = measured.get(k, 0) + v
        all_reports.extend(reports)

    # the kernel matrix: sub-interpreter findings share the baseline
    # currency (engine key "kernel/<cell>"); sanitizer violations mean
    # an UNSOUND transfer rule and fail the gate unconditionally
    kernel_cells = {}
    sanitizer_ok = True
    if not args.no_kernels:
        print("analyzing kernel matrix + differential sanitizer...",
              file=sys.stderr)
        for r in ana.run_kernel_matrix():
            san = r.pop("sanitizer")
            kernel_cells[r["engine"]] = dict(
                seconds=r["seconds"], sanitizer_ok=san["ok"],
                draws=san["n_draws"])
            if not san["ok"]:
                sanitizer_ok = False
                print(f"SANITIZER VIOLATION in {r['engine']}: "
                      f"{san['violations']}", file=sys.stderr)
            for f in r["findings"]:
                if f.severity == ana.ERROR:
                    n_err += f.count
                elif f.severity == ana.WARN:
                    n_warn += f.count
                else:
                    n_info += f.count
            for k, v in ana.key_counts(r["findings"]).items():
                measured[k] = measured.get(k, 0) + v
            all_reports.append(r)

    baseline = ana.load_baseline(args.baseline)
    new, stale = ana.diff_baseline(measured, baseline)

    if (new or stale) and args.update:
        by_key_note = {}
        for r in all_reports:
            for f in r["findings"]:
                if f.severity in ana.GATING:
                    by_key_note.setdefault(f.key, f.message)
        doc = {
            "_doc": "Grandfathered static-analysis findings "
                    "(scripts/check_analysis.py).  Keys are line-number-"
                    "free so refactors don't churn them; rewrite with "
                    "--update after an INTENTIONAL change and commit the "
                    "diff.  An empty table means the engines analyze "
                    "clean — keep it that way.",
            "grandfathered": {
                k: {"count": c, "note": by_key_note.get(k, "")}
                for k, c in sorted(measured.items())
            },
        }
        with open(args.baseline, "w") as f:
            json.dump(doc, f, indent=1)
        print(f"updated {args.baseline} ({len(measured)} grandfathered)",
              file=sys.stderr)
        new, stale = {}, {}

    if args.out:
        ana.export_findings(args.out, all_reports)

    ok = not new and sanitizer_ok
    print(json.dumps(dict(
        ok=ok, configs=sorted(names), errors=n_err, warnings=n_warn,
        infos=n_info, gating_sites=len(measured),
        sanitizer_ok=sanitizer_ok, kernel_cells=kernel_cells,
        new_findings=sorted(new), stale_baseline=sorted(stale))))
    if not sanitizer_ok:
        print("differential sanitizer VIOLATED: a kernel transfer rule "
              "is unsound (concrete values escape the abstract cells) — "
              "fix analysis/pallas.py or interp.py before trusting any "
              "kernel proof", file=sys.stderr)
    if new:
        print("NEW findings (fix, audit with layouts.audited, or "
              "consciously --update the baseline):", file=sys.stderr)
        for k in sorted(new):
            print(f"  {k} (+{new[k]})", file=sys.stderr)
    if stale:
        print("stale baseline entries (code no longer produces them; "
              "--update prunes):", file=sys.stderr)
        for k in sorted(stale):
            print(f"  {k}", file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
