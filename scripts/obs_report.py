"""Render an obs run log (``--metrics-out run.jsonl``) as one timeline.

Merges one or more obs JSONL files (interval metrics, trace events, span
begin/end — one shared monotonic clock per file, hermes_tpu/obs) and renders
the causally ordered run story: membership / fault events next to the
interval throughput they explain, plus the device phase histograms from the
final summary.  Usage:

    python -m hermes_tpu --steps 400 --report-every 50 \
        --freeze 2:100:200 --metrics-out run.jsonl
    python scripts/obs_report.py run.jsonl
    python scripts/obs_report.py run.jsonl --json   # merged records, stdout
"""

import argparse
import json
import sys

sys.path.insert(0, ".")

from hermes_tpu.obs import report  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("paths", nargs="+", help="obs JSONL run logs to merge")
    ap.add_argument("--max-timeline", type=int, default=None,
                    help="show only the last N timeline records")
    ap.add_argument("--json", action="store_true",
                    help="emit the merged record list as JSON instead of "
                    "the human report")
    args = ap.parse_args()

    records = report.load_records(args.paths)
    if args.json:
        json.dump(records, sys.stdout)
        sys.stdout.write("\n")
        return
    sys.stdout.write(report.render_report(records,
                                          max_timeline=args.max_timeline))


if __name__ == "__main__":
    main()
