"""Render an obs run log (``--metrics-out run.jsonl``) as one timeline.

Thin shim (round-18 satellite): the CLI moved to
``python -m hermes_tpu.obs.report`` — the profile.py pattern, where the
renderer is importable library code and its entry point lives beside it.
This script stays for muscle memory and old docs:

    python -m hermes_tpu --steps 400 --report-every 50 \
        --freeze 2:100:200 --metrics-out run.jsonl
    python -m hermes_tpu.obs.report run.jsonl
    python scripts/obs_report.py run.jsonl --json   # same thing
"""

import sys

sys.path.insert(0, ".")

from hermes_tpu.obs.report import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
