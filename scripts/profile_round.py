"""Honest phase-level profiling of the fast round on the target TPU.

Methodology (measured; see ARCHITECTURE.md): through the tunneled PJRT
runtime, execution is DEFERRED until the first device-to-host readback and
`block_until_ready` alone does not execute queued work — so this script (a)
forces synchronous mode with an initial readback, and (b) times scan-chunked
variants of the round with pieces ablated, attributing the difference.  Run:

    python scripts/profile_round.py [S] [C]
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import faststep as fst
from hermes_tpu.workload import ycsb

jax.device_get(jnp.zeros(8) + 1)  # force synchronous (honest) mode

S = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
C = int(sys.argv[2]) if len(sys.argv) > 2 else S // 2

cfg = HermesConfig(
    n_replicas=8, n_keys=1 << 20, value_words=8, n_sessions=S,
    replay_slots=256, ops_per_session=128, wrap_stream=True,
    lane_budget_cfg=C, rebroadcast_every=4, replay_scan_every=32,
    workload=WorkloadConfig(read_frac=0.5, seed=0),
)


def timed_chunk(round_fn, rounds=30, reps=3):
    fs = jax.device_put(fst.init_fast_state(cfg))
    stream = jax.device_put(fst.prep_stream(ycsb.make_streams(cfg)))

    @jax.jit
    def chunk(fs, stream, ctl):
        def body(carry, off):
            nxt = round_fn(ctl._replace(step=ctl.step + off), carry, stream)
            return nxt, None
        fs, _ = jax.lax.scan(body, fs, jnp.arange(rounds, dtype=jnp.int32))
        return fs

    fs = chunk(fs, stream, fst.make_fast_ctl(cfg, 0))
    jax.block_until_ready(fs)
    jax.device_get(jax.tree.map(lambda x: x.ravel()[0], fs))
    t0 = time.perf_counter()
    for c in range(1, 1 + reps):
        fs = chunk(fs, stream, fst.make_fast_ctl(cfg, c * rounds))
    jax.block_until_ready(fs)
    jax.device_get(jax.tree.map(lambda x: x.ravel()[0], fs))
    return (time.perf_counter() - t0) / reps / rounds * 1e3


def full(ctl, fs, stream):
    nxt, _ = fst.fast_round_batched(cfg, ctl, fs, stream)
    return nxt


def coordinate_only(ctl, fs, stream):
    fs2, *_ = fst._coordinate(cfg, ctl, fs, stream)
    return fs2


def through_apply_inv(ctl, fs, stream):
    fs2, lanes, slot_lane, taken_lane, *_ = fst._coordinate(cfg, ctl, fs, stream)
    fs3 = fst._apply_inv_lanes(cfg, ctl, fs2, lanes, taken_lane)
    return fs3


t_full = timed_chunk(full)
t_coord = timed_chunk(coordinate_only)
t_ainv = timed_chunk(through_apply_inv)
print(f"S={S} C={C}")
print(f"  full round          : {t_full:7.2f} ms")
print(f"  coordinate only     : {t_coord:7.2f} ms")
print(f"  + bcast + apply_inv : {t_ainv:7.2f} ms  (apply_inv ~= {t_ainv - t_coord:.2f})")
print(f"  acks+commit+val     : ~{t_full - t_ainv:.2f} ms (by difference)")
