"""Honest phase-level profiling of the fast round on the target TPU.

Promoted (round-6) into ``hermes_tpu.obs.profile`` — the per-fusion cost
ledger, the StableHLO op census, the obs-schema JSONL exporter and the
budget-gate predicate all live there now; this wrapper keeps the
historical entry point and argument shape:

    python scripts/profile_round.py [S] [C]

is exactly ``python -m hermes_tpu.obs.profile [S] [C]``.
"""

import os
import sys

# resolve the package from the repo root this script lives in (no
# cwd-dependent sys.path hack: the wrapper works from any directory)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hermes_tpu.obs.profile import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
