"""Obs-overhead smoke (CI): the instrumented fast round must be behaviorally
identical to — and not meaningfully slower than — the uninstrumented one.

Methodology (documented in ARCHITECTURE.md "Observability"):

  * Functional smoke, CPU backend, small shape (a scaled-down
    scripts/profile_round.py default): run the SAME op stream through the
    fast scan compiled with ``phase_metrics=True`` and ``False``.
  * Behavior gate (hard): every base Meta column (n_read / n_write / n_rmw /
    n_abort / lat_* / max_pts) must match EXACTLY between the two programs —
    instrumentation is pure measurement, it must never change a protocol
    outcome.  Phase columns must be populated under True and stay zero
    under False.
  * Timing gate: interleaved median-of-reps chunk wall time; the
    instrumented/uninstrumented ratio must stay under ``--max-overhead``
    (default 25% on CPU — host timing noise at smoke shape dwarfs the
    device-side cost; the on-TPU budget in the acceptance criteria is 5%,
    measured at the profile_round.py shape where the dense fused sums are
    amortized).  Round-18 de-noise: the two variants alternate inside ONE
    timing loop so machine-speed drift hits both equally (timing them
    back-to-back used to swing the ratio ±30% on a loaded box), the
    overhead is clamped at 0 (two noisy medians can subtract below zero,
    which used to record a meaningless ``overhead_frac: -0.04``), and
    every per-rep sample lands in the artifact so the gate's margin is
    visible.
  * Tracing leg (round-18, obs/tracing.py): the same clamped-median
    methodology applied one layer up — a KVS client burst with per-op
    tracing at ``--trace-sample`` (default 64) + an attached obs context,
    against the untraced/unattached build.  Behavior gate: base counters
    identical.  Timing gate: same ``--max-overhead`` ceiling.  (The round
    census being bit-identical under tracing is the census gate's job —
    scripts/check_op_census.py.)

Writes OBS_OVERHEAD.json; exits non-zero on any gate failure.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# round-20: instrumentation must not measure instrumentation — the lock
# sanitizer (HERMES_LOCKLINT=1 swaps serving locks for ObsLock, feeding
# lock_* hold-time series into any attached registry) would inflate the
# traced leg against the untraced one.  Force it OFF here regardless of
# the caller's env; build_traced_runner additionally asserts no lock_*
# metric ever reaches the traced registry.
os.environ["HERMES_LOCKLINT"] = "0"

import jax  # noqa: E402
import numpy as np  # noqa: E402

from hermes_tpu.config import HermesConfig, WorkloadConfig  # noqa: E402
from hermes_tpu.core import faststep as fst  # noqa: E402
from hermes_tpu.workload import ycsb  # noqa: E402

BASE_COLS = ("n_read", "n_write", "n_rmw", "n_abort",
             "lat_sum", "lat_cnt", "lat_hist", "max_pts")
PHASE_COLS = ("n_inv", "n_rebcast", "n_nack", "n_retry",
              "replay_peak", "qwait_sum", "qwait_hist")


def _cfg(phase_metrics: bool) -> HermesConfig:
    # scaled-down profile_round.py default shape (smoke, not timing truth)
    return HermesConfig(
        n_replicas=4, n_keys=1 << 12, value_words=2, n_sessions=256,
        replay_slots=32, ops_per_session=64, wrap_stream=True,
        lane_budget_cfg=128, rebroadcast_every=4, replay_scan_every=32,
        phase_metrics=phase_metrics,
        workload=WorkloadConfig(read_frac=0.5, seed=0),
    )


def build_runner(phase_metrics: bool, rounds: int, chunks: int):
    """Compile + warm one fast-scan variant; returns (meta, run_fn)."""
    cfg = _cfg(phase_metrics)
    chunk = fst.build_fast_scan(cfg, rounds)
    stream = jax.device_put(fst.prep_stream(ycsb.make_streams(cfg)))

    def full_run():
        fs = jax.device_put(fst.init_fast_state(cfg))
        for c in range(chunks):
            fs = chunk(fs, stream, fst.make_fast_ctl(cfg, c * rounds))
        jax.block_until_ready(fs)
        return fs

    fs = full_run()  # compile + the meta the behavior gate compares
    return jax.device_get(fs.meta), full_run


def build_traced_runner(trace_sample: int, n_ops: int):
    """Compile + warm one KVS client-burst variant, traced (sampler + obs
    attached) or untraced — the layer where the round-18 tracing cost lives
    (the compiled round cannot see the sampler; the census gate proves that
    separately).  Returns (burst_fn, counts_fn)."""
    from hermes_tpu.kvs import KVS
    from hermes_tpu.obs import Observability

    cfg = HermesConfig(
        n_replicas=3, n_keys=256, value_words=4, n_sessions=32,
        replay_slots=8, ops_per_session=4, pipeline_depth=2,
        trace_sample=trace_sample,
        workload=WorkloadConfig(read_frac=0.5, seed=0),
    )
    kv = KVS(cfg, backend="batched")
    obs = None
    if trace_sample:
        obs = Observability()
        kv.rt.attach_obs(obs)

    def burst():
        futs = []
        for i in range(n_ops):
            r, s, k = i % 3, i % 32, i % 256
            futs.append(kv.put(r, s, k, [i, i + 1]) if i % 2
                        else kv.get(r, s, k))
        assert kv.run_until(futs), "burst did not drain"

    def counts():
        c = kv.rt.counters()
        return {k: int(np.asarray(c[k]).sum())
                for k in ("n_read", "n_write", "n_rmw", "n_abort")}

    burst()  # warm: compile + host caches
    if obs is not None:
        from hermes_tpu.analysis.lockgraph import LOCK_METRIC_PREFIX

        leaked = [n for n in obs.registry.names()
                  if n.startswith(LOCK_METRIC_PREFIX)]
        assert not leaked, (
            f"lock sanitizer series leaked into the overhead gate's "
            f"traced registry: {leaked} — HERMES_LOCKLINT must stay off "
            f"here (instrumentation measuring instrumentation)")
    return burst, counts


def time_interleaved(runners, reps: int):
    """One timing loop over all variants, alternating within each rep, so
    machine-speed drift lands on every variant equally.  Returns
    (medians, per-rep times), parallel to ``runners``."""
    times = [[] for _ in runners]
    for _ in range(reps):
        for i, run in enumerate(runners):
            t0 = time.perf_counter()
            run()
            times[i].append(time.perf_counter() - t0)
    return [sorted(t)[reps // 2] for t in times], times


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", type=int, default=20)
    ap.add_argument("--chunks", type=int, default=2)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--max-overhead", type=float, default=0.25,
                    help="instrumented/uninstrumented wall-time ratio gate "
                    "(CPU smoke default 0.25; the TPU budget is 0.05)")
    ap.add_argument("--trace-sample", type=int, default=64,
                    help="1-in-N op tracing rate for the tracing leg "
                    "(0 skips the leg)")
    ap.add_argument("--trace-ops", type=int, default=192,
                    help="client ops per burst in the tracing leg")
    ap.add_argument("--out", default="OBS_OVERHEAD.json")
    args = ap.parse_args()

    meta_on, run_on = build_runner(True, args.rounds, args.chunks)
    meta_off, run_off = build_runner(False, args.rounds, args.chunks)
    (t_on, t_off), (times_on, times_off) = time_interleaved(
        [run_on, run_off], args.reps)

    failures = []
    for col in BASE_COLS:
        a, b = np.asarray(getattr(meta_on, col)), np.asarray(
            getattr(meta_off, col))
        if not np.array_equal(a, b):
            failures.append(
                f"base column {col} diverged between instrumented and "
                f"uninstrumented runs (sum {a.sum()} vs {b.sum()}) — "
                f"instrumentation changed protocol behavior")
    if int(np.asarray(meta_on.n_inv).sum()) == 0:
        failures.append("instrumented run recorded no INV broadcasts "
                        "(phase counters dead)")
    if int(np.asarray(meta_on.qwait_hist).sum()) == 0:
        failures.append("instrumented run recorded an empty quorum-wait "
                        "histogram")
    for col in PHASE_COLS:
        if np.asarray(getattr(meta_off, col)).any():
            failures.append(f"uninstrumented run wrote phase column {col}")

    # clamp at 0: two noisy medians can subtract below zero on CPU, and a
    # negative "overhead" in the artifact is noise masquerading as signal
    overhead = max(0.0, (t_on - t_off) / t_off) if t_off > 0 else 0.0
    if overhead > args.max_overhead:
        failures.append(
            f"instrumentation overhead {overhead:.1%} exceeds "
            f"{args.max_overhead:.0%} gate (median {t_on*1e3:.1f} ms vs "
            f"{t_off*1e3:.1f} ms over {args.rounds * args.chunks} rounds)")

    traced = None
    if args.trace_sample > 0:
        burst_tr, counts_fn_tr = build_traced_runner(
            args.trace_sample, args.trace_ops)
        burst_un, counts_fn_un = build_traced_runner(0, args.trace_ops)
        (t_tr, t_un), (times_tr, times_un) = time_interleaved(
            [burst_tr, burst_un], args.reps)
        counts_tr, counts_un = counts_fn_tr(), counts_fn_un()
        if counts_tr != counts_un:
            failures.append(
                f"tracing changed KVS behavior: counters {counts_tr} "
                f"(traced 1/{args.trace_sample}) vs {counts_un} (untraced)")
        trace_overhead = max(0.0, (t_tr - t_un) / t_un) if t_un > 0 else 0.0
        if trace_overhead > args.max_overhead:
            failures.append(
                f"tracing overhead {trace_overhead:.1%} at sample rate "
                f"1/{args.trace_sample} exceeds {args.max_overhead:.0%} gate "
                f"(median {t_tr*1e3:.1f} ms vs {t_un*1e3:.1f} ms per "
                f"{args.trace_ops}-op burst)")
        traced = dict(
            trace_sample=args.trace_sample,
            ops_per_burst=args.trace_ops,
            wall_s_traced=round(t_tr, 4),
            wall_s_untraced=round(t_un, 4),
            trace_overhead_frac=round(trace_overhead, 4),
            times_traced=[round(t, 4) for t in times_tr],
            times_untraced=[round(t, 4) for t in times_un],
            counters=counts_tr,
        )

    out = dict(
        rounds=args.rounds * args.chunks,
        reps=args.reps,
        wall_s_instrumented=round(t_on, 4),
        wall_s_uninstrumented=round(t_off, 4),
        overhead_frac=round(overhead, 4),
        max_overhead=args.max_overhead,
        times_instrumented=[round(t, 4) for t in times_on],
        times_uninstrumented=[round(t, 4) for t in times_off],
        commits=int(np.asarray(meta_on.n_write).sum()
                    + np.asarray(meta_on.n_rmw).sum()),
        n_inv=int(np.asarray(meta_on.n_inv).sum()),
        traced=traced,
        platform=jax.devices()[0].platform,
        ok=not failures,
        failures=failures,
    )
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
