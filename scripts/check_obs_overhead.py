"""Obs-overhead smoke (CI): the instrumented fast round must be behaviorally
identical to — and not meaningfully slower than — the uninstrumented one.

Methodology (documented in ARCHITECTURE.md "Observability"):

  * Functional smoke, CPU backend, small shape (a scaled-down
    scripts/profile_round.py default): run the SAME op stream through the
    fast scan compiled with ``phase_metrics=True`` and ``False``.
  * Behavior gate (hard): every base Meta column (n_read / n_write / n_rmw /
    n_abort / lat_* / max_pts) must match EXACTLY between the two programs —
    instrumentation is pure measurement, it must never change a protocol
    outcome.  Phase columns must be populated under True and stay zero
    under False.
  * Timing gate: median-of-reps chunk wall time; the instrumented/
    uninstrumented ratio must stay under ``--max-overhead`` (default 25% on
    CPU — host timing noise at smoke shape dwarfs the device-side cost; the
    on-TPU budget in the acceptance criteria is 5%, measured at the
    profile_round.py shape where the dense fused sums are amortized).

Writes OBS_OVERHEAD.json; exits non-zero on any gate failure.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import numpy as np  # noqa: E402

from hermes_tpu.config import HermesConfig, WorkloadConfig  # noqa: E402
from hermes_tpu.core import faststep as fst  # noqa: E402
from hermes_tpu.workload import ycsb  # noqa: E402

BASE_COLS = ("n_read", "n_write", "n_rmw", "n_abort",
             "lat_sum", "lat_cnt", "lat_hist", "max_pts")
PHASE_COLS = ("n_inv", "n_rebcast", "n_nack", "n_retry",
              "replay_peak", "qwait_sum", "qwait_hist")


def _cfg(phase_metrics: bool) -> HermesConfig:
    # scaled-down profile_round.py default shape (smoke, not timing truth)
    return HermesConfig(
        n_replicas=4, n_keys=1 << 12, value_words=2, n_sessions=256,
        replay_slots=32, ops_per_session=64, wrap_stream=True,
        lane_budget_cfg=128, rebroadcast_every=4, replay_scan_every=32,
        phase_metrics=phase_metrics,
        workload=WorkloadConfig(read_frac=0.5, seed=0),
    )


def run_variant(phase_metrics: bool, rounds: int, chunks: int, reps: int):
    cfg = _cfg(phase_metrics)
    chunk = fst.build_fast_scan(cfg, rounds)
    stream = jax.device_put(fst.prep_stream(ycsb.make_streams(cfg)))

    def full_run():
        fs = jax.device_put(fst.init_fast_state(cfg))
        for c in range(chunks):
            fs = chunk(fs, stream, fst.make_fast_ctl(cfg, c * rounds))
        jax.block_until_ready(fs)
        return fs

    fs = full_run()  # compile + the meta the behavior gate compares
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        full_run()
        times.append(time.perf_counter() - t0)
    return jax.device_get(fs.meta), sorted(times)[reps // 2]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", type=int, default=20)
    ap.add_argument("--chunks", type=int, default=2)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--max-overhead", type=float, default=0.25,
                    help="instrumented/uninstrumented wall-time ratio gate "
                    "(CPU smoke default 0.25; the TPU budget is 0.05)")
    ap.add_argument("--out", default="OBS_OVERHEAD.json")
    args = ap.parse_args()

    meta_on, t_on = run_variant(True, args.rounds, args.chunks, args.reps)
    meta_off, t_off = run_variant(False, args.rounds, args.chunks, args.reps)

    failures = []
    for col in BASE_COLS:
        a, b = np.asarray(getattr(meta_on, col)), np.asarray(
            getattr(meta_off, col))
        if not np.array_equal(a, b):
            failures.append(
                f"base column {col} diverged between instrumented and "
                f"uninstrumented runs (sum {a.sum()} vs {b.sum()}) — "
                f"instrumentation changed protocol behavior")
    if int(np.asarray(meta_on.n_inv).sum()) == 0:
        failures.append("instrumented run recorded no INV broadcasts "
                        "(phase counters dead)")
    if int(np.asarray(meta_on.qwait_hist).sum()) == 0:
        failures.append("instrumented run recorded an empty quorum-wait "
                        "histogram")
    for col in PHASE_COLS:
        if np.asarray(getattr(meta_off, col)).any():
            failures.append(f"uninstrumented run wrote phase column {col}")

    overhead = (t_on - t_off) / t_off if t_off > 0 else 0.0
    if overhead > args.max_overhead:
        failures.append(
            f"instrumentation overhead {overhead:.1%} exceeds "
            f"{args.max_overhead:.0%} gate (median {t_on*1e3:.1f} ms vs "
            f"{t_off*1e3:.1f} ms over {args.rounds * args.chunks} rounds)")

    out = dict(
        rounds=args.rounds * args.chunks,
        reps=args.reps,
        wall_s_instrumented=round(t_on, 4),
        wall_s_uninstrumented=round(t_off, 4),
        overhead_frac=round(overhead, 4),
        max_overhead=args.max_overhead,
        commits=int(np.asarray(meta_on.n_write).sum()
                    + np.asarray(meta_on.n_rmw).sum()),
        n_inv=int(np.asarray(meta_on.n_inv).sum()),
        platform=jax.devices()[0].platform,
        ok=not failures,
        failures=failures,
    )
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
