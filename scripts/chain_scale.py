"""Closed-loop zipfian chaining evidence at bench-relevant scales (round-3
verdict item 6): measure commits/round for the contended config-3 shape
(scrambled Zipfian-0.99, 50/50 mix) under the race arbiter vs
sort+chain_writes, at three session scales up to the full 262k-session
bench shape (8 x 32768) — replacing the round-3 extrapolation from 8x2048
with measurements.

Usage (CPU, scrubbed env)::

    env PYTHONPATH=/root/repo PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python scripts/chain_scale.py

On the chip, run with the default env.  Writes CHAIN_SCALE.json and prints
one JSON line per cell.
"""

import argparse
import json
import sys
import time

import jax

SCALES = (2048, 8192, 32768)  # sessions per replica; 8 replicas
CELLS = (("race", 0), ("sort", 0), ("sort", 128))


def run_cell(sessions: int, arb: str, chain: int, rounds: int,
             warmup: int) -> dict:
    """One (scale, arbiter) cell.  ``warmup`` rounds run first and are
    excluded: the closed loop starts with every session on a fresh
    (mostly-distinct) key, so early rounds overstate the contended steady
    state the evidence is about."""
    from hermes_tpu.config import HermesConfig, WorkloadConfig
    from hermes_tpu.core import faststep as fst
    from hermes_tpu.workload import ycsb

    cfg = HermesConfig(
        n_replicas=8, n_keys=1 << 20, value_words=8, n_sessions=sessions,
        replay_slots=256, ops_per_session=256, wrap_stream=True,
        device_stream=True, lane_budget_cfg=max(1024, (3 * sessions) // 4),
        read_unroll=2, rebroadcast_every=4, replay_scan_every=32,
        arb_mode=arb, chain_writes=chain,
        workload=WorkloadConfig(read_frac=0.5, seed=0,
                                distribution="zipfian", zipf_theta=0.99),
    )
    fs = jax.device_put(fst.init_fast_state(cfg))
    stream = jax.device_put(fst.prep_stream(ycsb.stub_stream(cfg)))
    wchunk = fst.build_fast_scan(cfg, warmup, donate=True)
    chunk = fst.build_fast_scan(cfg, rounds, donate=True)

    def commits(x):
        m = jax.device_get(x.meta)
        return int(m.n_write.sum() + m.n_rmw.sum())

    fs = wchunk(fs, stream, fst.make_fast_ctl(cfg, 0))
    jax.block_until_ready(fs)
    c0 = commits(fs)  # drains warmup; forces synchronous link mode
    t0 = time.perf_counter()
    fs = chunk(fs, stream, fst.make_fast_ctl(cfg, warmup))
    jax.block_until_ready(fs)
    c1 = commits(fs)
    wall = time.perf_counter() - t0
    return {
        "sessions_per_replica": sessions,
        "total_sessions": 8 * sessions,
        "arb": arb,
        "chain_writes": chain,
        "rounds": rounds,
        "commits_per_round": round((c1 - c0) / rounds, 1),
        "writes_per_sec": round((c1 - c0) / wall, 1),
        "round_ms": round(wall / rounds * 1e3, 2),
        "platform": jax.devices()[0].platform,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=40)
    ap.add_argument("--warmup", type=int, default=60)
    args = ap.parse_args()
    out = []
    for sessions in SCALES:
        base = None
        for arb, chain in CELLS:
            r = run_cell(sessions, arb, chain, args.rounds, args.warmup)
            if arb == "race":
                base = r["commits_per_round"]
            elif base:
                r["vs_race"] = round(r["commits_per_round"] / base, 2)
            out.append(r)
            print(json.dumps(r), file=sys.stderr, flush=True)
    with open("CHAIN_SCALE.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"cells": len(out), "file": "CHAIN_SCALE.json"}))


if __name__ == "__main__":
    main()
