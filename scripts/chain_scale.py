"""Closed-loop zipfian chaining evidence at bench-relevant scales (round-3
verdict item 6): measure commits/round for the contended config-3 shape
(scrambled Zipfian-0.99, 50/50 mix) under the race arbiter vs
sort+chain_writes, at three session scales up to the full 262k-session
bench shape (8 x 32768) — replacing the round-3 extrapolation from 8x2048
with measurements.

Every cell runs through ``bench.run_mix`` (the shared cell-runner) with
shape overrides, so the evidence measures exactly what bench.py runs.  A
warmup phase is excluded: the closed loop starts with every session on a
fresh (mostly-distinct) key, so early rounds overstate the contended
steady state.

Usage (CPU, scrubbed env)::

    env PYTHONPATH=/root/repo PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
        python scripts/chain_scale.py

On the chip, run with the default env.  Writes CHAIN_SCALE.json and prints
one JSON line per cell.
"""

import argparse
import json
import sys

sys.path.insert(0, ".")

import bench

SCALES = (2048, 8192, 32768)  # sessions per replica; 8 replicas
CELLS = (("race", 0), ("sort", 0), ("sort", 128))


def run_cell(sessions, arb, chain, rounds, warmup):
    over = dict(n_sessions=sessions,
                lane_budget_cfg=max(1024, (3 * sessions) // 4),
                arb_mode=arb, chain_writes=chain)
    half = max(1, rounds // 2)  # two measured chunks of this size
    r = bench.run_mix("zipfian", over=over, rounds=half, chunks=2,
                      warmup_chunks=max(1, warmup // half))
    rec = dict(
        sessions_per_replica=sessions, total_sessions=8 * sessions,
        arb=arb, chain_writes=chain, rounds=r["rounds"],
        commits_per_round=round(r["commits"] / r["rounds"], 1),
        writes_per_sec=r["writes_per_sec"],
        round_ms=round(r["round_us"] / 1e3, 2), platform=r["platform"],
    )
    print(json.dumps(rec), file=sys.stderr, flush=True)
    return rec


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=40)
    ap.add_argument("--warmup", type=int, default=60)
    args = ap.parse_args()
    out = []
    for sessions in SCALES:
        base = None
        for arb, chain in CELLS:
            r = run_cell(sessions, arb, chain, args.rounds, args.warmup)
            if arb == "race":
                base = r["commits_per_round"]
            elif base:
                r["vs_race"] = round(r["commits_per_round"] / base, 2)
            out.append(r)
    with open("CHAIN_SCALE.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"cells": len(out), "file": "CHAIN_SCALE.json"}))


if __name__ == "__main__":
    main()
