"""Quantify the sharded (transport=tpu_ici) round against the batched
lockstep emulation (round-4 verdict item 1).

Every headline bench number is the BATCHED engine: 8 replicas' protocol
work on one chip, acks derived without a wire.  The real per-chip program
(`fast_round_sharded`) additionally pays the lane->slot wire compaction,
the ack collective + slot->lane routing, and the VAL bit gather.  This
script makes that delta a number three ways:

  1. **Op census** — lower BOTH single-round programs at the exact bench
     shape (abstract: no arrays materialized) and count the sparse
     (gather/scatter/sort) and collective (all_gather/all_to_all) StableHLO
     ops per round.  Backend-independent by construction.
  2. **Measured ratio** — time scan-chunked batched vs sharded rounds on
     the 8-device virtual CPU mesh at a CPU-tractable shape; report
     ms/round and the sharded/batched ratio.  (The CPU backend's op costs
     differ from the TPU's, so this is corroboration, not the projection.)
  3. **v5e-8 projection** — apply the measured TPU cost model
     (ARCHITECTURE.md: round time ~= #sparse-ops-on-chain x ~1.3-2.4 ms,
     nearly size-independent, even inside lax.scan) to the census delta,
     plus an ICI-volume estimate for the collectives, against the measured
     batched round time from BENCH_MIXES.json.

Writes SHARDED_CENSUS.json.  Run on the CPU env (the census + ratio need 8
devices, not a chip):

    env JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/sharded_census.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, ".")

if "--tpu-r1" not in sys.argv:
    # census + CPU-mesh ratio need 8 virtual devices, never the chip;
    # --tpu-r1 (the on-chip routing-delta cell) keeps the default env
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")

import jax
import numpy as np
from jax.sharding import Mesh

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import faststep as fst
from hermes_tpu.obs.profile import (  # single source of the cost model
    COST_HI, COST_LO, COST_MID, op_census)
from hermes_tpu.workload import ycsb


def bench_cfg():
    import bench

    return bench._cfg("a")


def census(cfg, backend: str, mesh=None) -> dict:
    """StableHLO op counts of ONE protocol round at cfg's shape — the
    canonical implementation lives in hermes_tpu.obs.profile (round-6);
    this wrapper keeps the historical entry point."""
    return op_census(cfg, backend, mesh)


def _prep_backend(cfg, mesh, backend: str, rounds: int):
    """Build the scan chunk + placed state for one backend (shared by the
    CPU-mesh ratio and the on-chip R=1 cell, so the two cells cannot
    drift in setup)."""
    if backend == "batched":
        chunk = fst.build_fast_scan(cfg, rounds, donate=True)
        fs = jax.device_put(fst.init_fast_state(cfg))
        stream = jax.device_put(fst.prep_stream(ycsb.stub_stream(cfg)))
    else:
        chunk = fst.build_fast_sharded(cfg, mesh, rounds=rounds, donate=True)
        fs = fst.init_fast_state(cfg, n_local=cfg.n_replicas)
        stream = fst.prep_stream(ycsb.stub_stream(cfg))
        fs, stream = fst.place_fast_sharded(cfg, mesh, fs, stream)
    return chunk, fs, stream


def _chunk_wall(cfg, mesh, backend: str, rounds: int, reps: int) -> float:
    """Median wall seconds of one `rounds`-round chunk dispatch (synced
    per rep)."""
    chunk, fs, stream = _prep_backend(cfg, mesh, backend, rounds)
    fs = chunk(fs, stream, fst.make_fast_ctl(cfg, 0))
    jax.block_until_ready(fs)
    jax.device_get(jax.tree.leaves(fs)[0].ravel()[:1])  # sync link mode
    ts = []
    for c in range(1, 1 + reps):
        t0 = time.perf_counter()
        fs = chunk(fs, stream, fst.make_fast_ctl(cfg, c * rounds))
        jax.block_until_ready(fs)
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def _slope_ms_per_round(cfg, mesh, backend: str, n_lo=10, n_hi=60,
                        reps=5) -> float:
    """ms/round as the slope between two chunk sizes — the per-dispatch
    host handshake (and its jitter) cancels, same method as
    bench.run_latency's device_round_us."""
    t_lo = _chunk_wall(cfg, mesh, backend, n_lo, reps)
    t_hi = _chunk_wall(cfg, mesh, backend, n_hi, reps)
    return (t_hi - t_lo) / (n_hi - n_lo) * 1e3


def measured_ratio(rounds=20, reps=3) -> dict:
    """ms/round of batched vs sharded scan chunks on the 8-CPU mesh at a
    CPU-tractable fixed shape (same cfg, same seed, same rounds).  CPU
    dispatch overhead is negligible, so plain per-chunk timing suffices."""
    cfg = HermesConfig(
        n_replicas=8, n_keys=1 << 16, value_words=8, n_sessions=2048,
        replay_slots=64, ops_per_session=64, wrap_stream=True,
        device_stream=True, arb_mode="sort", chain_writes=128,
        lane_budget_cfg=(3 * 2048) // 4, rebroadcast_every=4,
        replay_scan_every=32,
        workload=WorkloadConfig(read_frac=0.5, seed=0),
    )
    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    t_b = _chunk_wall(cfg, mesh, "batched", rounds, reps) / rounds * 1e3
    t_s = _chunk_wall(cfg, mesh, "sharded", rounds, reps) / rounds * 1e3
    return dict(shape=dict(n_keys=cfg.n_keys, n_sessions=cfg.n_sessions,
                           lane_budget=cfg.lane_budget, rounds=rounds),
                batched_ms_per_round=round(t_b, 2),
                sharded_ms_per_round=round(t_s, 2),
                ratio=round(t_s / t_b, 3))


def projection(cen_b: dict, cen_s: dict) -> dict:
    """v5e-8 projection from the census delta + the measured TPU cost model
    + an ICI-volume estimate, anchored on the measured batched round."""
    cfg = bench_cfg()
    C, V = cfg.lane_budget, cfg.value_words
    R = cfg.n_replicas
    # measured batched operating point (BENCH_MIXES.json round-4/5)
    try:
        with open("BENCH_MIXES.json") as f:
            mixes = json.load(f)
        a = mixes["a"]
        round_ms = a["round_us"] / 1e3
        wps = a["writes_per_sec"]
    except Exception:
        round_ms, wps = 28.6, 13.68e6  # round-4 recorded values
    d_sparse = cen_s["sparse_total"] - cen_b["sparse_total"]
    lo, mid, hi = COST_LO, COST_MID, COST_HI
    # ICI bytes per chip per round: INV block (pkf+pts 8 B + val 4V B) and
    # VAL bits gathered from the other R-1 chips; ack words exchanged
    # all_to_all (pkf+pts 8 B) with R-1 peers
    inv_b = (8 + 4 * V) * C * (R - 1)
    ack_b = 8 * C * (R - 1)
    val_b = C * (R - 1)
    total_mb = (inv_b + ack_b + val_b) / 1e6
    # v5e ICI: O(100) GB/s effective per chip; quote a conservative range
    ici_ms = dict(at_45GBps=round(total_mb / 45, 3),
                  at_100GBps=round(total_mb / 100, 3))
    commits_per_round = wps * round_ms / 1e3
    proj = {}
    for name, per_op in (("optimistic", lo), ("central", mid),
                         ("pessimistic", hi)):
        rt = round_ms + d_sparse * per_op + total_mb / (
            45 if name == "pessimistic" else 100)
        proj[name] = dict(
            round_ms=round(rt, 2),
            aggregate_writes_per_sec=round(commits_per_round / rt * 1e3, 0),
            vs_10M_target=round(commits_per_round / rt * 1e3 / 1e7, 3),
            vs_batched=round(round_ms / rt, 3),
        )
    return dict(
        anchored_on=dict(batched_round_ms=round_ms, batched_wps=wps),
        sparse_delta_per_round=d_sparse,
        per_sparse_op_ms=dict(lo=lo, mid=mid, hi=hi),
        ici_mb_per_chip_per_round=round(total_mb, 2),
        ici_ms=ici_ms,
        projected=proj,
    )


def mega_projection(cen_b: dict, cen_bm: dict) -> dict:
    """Round-15 modeled projection for the batched mega path, anchored on
    the measured batched round: removed launch-taxed sparse ops priced by
    the measured cost model; ADDED kernel launches and the serial kernel
    interiors priced by the Pallas ledger (PALLAS_PROBE.json's ~6 ns/iter
    serial cell, bracketed).  The ledger's static bound over-counts the
    apply kernel (its two phase loops are phase-exclusive at runtime) and
    never amortizes the cond-gated replay scan, so the central/optimistic
    scenarios use the EXECUTED-iteration estimate and only the
    pessimistic corner pays the full static bound — stated so the on-chip
    A/B (scripts/mega_compare.py) is understood as REQUIRED evidence, not
    a formality."""
    from hermes_tpu.obs.profile import (SERIAL_NS_HI, SERIAL_NS_LO,
                                        SERIAL_NS_MID)

    cfg = bench_cfg()
    R, L, RS = cfg.n_replicas, cfg.n_lanes, cfg.replay_slots
    try:
        with open("BENCH_MIXES.json") as f:
            a = json.load(f)["a"]
        round_ms, wps = a["round_us"] / 1e3, a["writes_per_sec"]
    except Exception:
        round_ms, wps = 28.6, 13.68e6
    d_sparse = cen_b["sparse_total"] - cen_bm["sparse_total"]
    d_calls = cen_bm["pallas_calls"] - cen_b["pallas_calls"]
    bound = cen_bm["pallas_serial_iter_bound"]
    # executed iterations per round: route (R*L) + apply (two phases over
    # R*L each) + the replay scan amortized over its cond period.  The
    # replay remainder is clamped at 0: if the ledger's static bound
    # ever under-reports (e.g. an unparseable grid dim), the projection
    # must degrade toward the bound-free estimate, never go negative.
    executed = (3 * R * L
                + max(0, bound - 5 * R * L) // max(1,
                                                   cfg.replay_scan_every))
    commits_per_round = wps * round_ms / 1e3
    proj = {}
    for name, op_ms, ns, iters, launch in (
            ("optimistic", COST_HI, SERIAL_NS_LO, executed, 0.3),
            ("central", COST_MID, SERIAL_NS_MID, executed, 0.5),
            ("pessimistic", COST_LO, SERIAL_NS_HI, bound, 1.0)):
        rt = round_ms - d_sparse * op_ms + d_calls * launch + iters * ns / 1e6
        proj[name] = dict(
            round_ms=round(rt, 2),
            writes_per_sec=round(commits_per_round / rt * 1e3, 0),
            vs_plateau=round(round_ms / rt, 3),
        )
    return dict(
        anchored_on=dict(batched_round_ms=round_ms, batched_wps=wps),
        sparse_removed=d_sparse, kernel_launches_added=d_calls,
        serial_iters=dict(executed_estimate=executed, static_bound=bound,
                          ns_per_iter=[SERIAL_NS_LO, SERIAL_NS_MID,
                                       SERIAL_NS_HI]),
        projected=proj,
        note=("modeled only — the serial-interior cost is the decisive "
              "unknown; run scripts/mega_compare.py on the chip before "
              "flipping mega_round on by default"),
    )


def tpu_r1_delta() -> dict:
    """Measure the sharded round's wire-routing overhead ON the real chip
    at a 1-replica mesh, via chunk-size slope (handshake cancelled,
    median-of-5 per size — the same method as bench.run_latency).

    Scope, stated honestly: at R=1 the collectives degenerate, and the
    routing ops whose extent is per-DESTINATION — the lane->slot wire
    compaction take_along (C slots × the full 48 B row), the VAL slot
    take_along, the slot->lane ack scatter — run at the true bench slot
    count; but the SOURCE-shaped extents (the per-slot post-arbiter
    gather and the ack-match tensor, (Rsrc, C)) are 8× smaller than at
    bench R=8.  A ~0 delta here therefore bounds the destination-shaped
    routing cost only; the source-shaped remainder stays model-priced in
    the projection bracket.  Run under the default TPU env
    (`python scripts/sharded_census.py --tpu-r1`)."""
    import bench as bench_mod

    cfg = bench_mod._cfg("a", over=dict(n_replicas=1))
    mesh = Mesh(np.array(jax.devices()[:1]), ("replica",))
    t_b = _slope_ms_per_round(cfg, mesh, "batched")
    t_s = _slope_ms_per_round(cfg, mesh, "sharded")
    d_sparse = None
    try:
        with open("SHARDED_CENSUS.json") as f:
            cen = json.load(f)["census"]
        d_sparse = (cen["sharded"]["sparse_total"]
                    - cen["batched"]["sparse_total"])
    except Exception:
        pass
    return dict(shape=dict(n_replicas=1, n_sessions=cfg.n_sessions,
                           lane_budget=cfg.lane_budget),
                platform=jax.devices()[0].platform,
                method="slope between 10- and 60-round chunks, median-of-5",
                batched_ms_per_round=round(t_b, 2),
                sharded_ms_per_round=round(t_s, 2),
                routing_delta_ms=round(t_s - t_b, 2),
                census_sparse_delta=d_sparse,
                model_predicted_delta_ms=(
                    None if d_sparse is None else
                    [round(d_sparse * COST_LO, 1),
                     round(d_sparse * COST_HI, 1)]),
                scope="destination-shaped routing ops at true slot count; "
                      "source-shaped (Rsrc,C) extents are 8x smaller than "
                      "bench R=8 and stay model-priced")


def main() -> None:
    if "--tpu-r1" in sys.argv:
        out = tpu_r1_delta()
        print(json.dumps(out))
        with open("SHARDED_CENSUS.json") as f:
            full = json.load(f)
        full["tpu_r1_delta"] = out
        with open("SHARDED_CENSUS.json", "w") as f:
            json.dump(full, f, indent=1)
        return
    cfg = bench_cfg()
    mesh = Mesh(np.array(jax.devices()[:8]), ("replica",))
    print("census at bench shape "
          f"(S={cfg.n_sessions}, C={cfg.lane_budget}, K={cfg.n_keys})...",
          file=sys.stderr)
    cen_b = census(cfg, "batched")
    cen_s = census(cfg, "sharded", mesh)
    print(f"  batched: {cen_b}", file=sys.stderr)
    print(f"  sharded: {cen_s}", file=sys.stderr)
    print("measuring CPU-mesh ratio...", file=sys.stderr)
    ratio = measured_ratio()
    print(f"  {ratio}", file=sys.stderr)
    proj = projection(cen_b, cen_s)
    from hermes_tpu.obs.profile import census_shape

    out = dict(
        bench_shape=census_shape(cfg),
        census=dict(batched=cen_b, sharded=cen_s),
        cpu_mesh_ratio=ratio,
        v5e8_projection=proj,
    )
    try:
        # a CPU regeneration must not discard the chip-measured cell: the
        # census/ratio/projection are backend-independent or CPU-sourced,
        # the tpu_r1 routing delta is TPU-only and carries over
        with open("SHARDED_CENSUS.json") as f:
            prev = json.load(f)
        if "tpu_r1_delta" in prev:
            out["tpu_r1_delta"] = prev["tpu_r1_delta"]
    except FileNotFoundError:
        pass
    except Exception as e:
        # the cell is irreplaceable without a chip — losing it must be LOUD
        print(f"WARNING: could not carry tpu_r1_delta over from the "
              f"existing SHARDED_CENSUS.json ({e}); re-run the chip cell "
              f"(--tpu-r1) to restore it", file=sys.stderr)
    with open("SHARDED_CENSUS.json", "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(dict(
        sparse_batched=cen_b["sparse_total"],
        sparse_sharded=cen_s["sparse_total"],
        collectives_sharded=cen_s["collective_total"],
        cpu_ratio=ratio["ratio"],
        projected_central_wps=proj["projected"]["central"][
            "aggregate_writes_per_sec"],
    )))


if __name__ == "__main__":
    main()
