#!/bin/bash
# Sequential on-chip artifact run (ONE TPU process at a time; no timeouts —
# killing a claim mid-flight wedges the tunneled chip for an hour+).
#   bash scripts/run_artifacts.sh
set -u
cd "$(dirname "$0")/.."
rc=0

echo "=== bench (all mixes + latency) ===" >&2
python bench.py --mix all 2>>artifacts_run.log || rc=1
echo "=== arbitration/chaining matrix ===" >&2
python scripts/arb_compare.py 2>>artifacts_run.log || rc=1
echo "=== checked bench window ===" >&2
python scripts/checked_bench.py --rounds 30 2>>artifacts_run.log || rc=1
echo "=== full-scale acceptance (scale=1.0, all keys checked) ===" >&2
python scripts/full_acceptance.py --scale 1.0 --max-steps 20000 2>>artifacts_run.log || rc=1
echo "=== done (rc=$rc) ===" >&2
exit $rc
