"""The Pallas-vs-XLA table-step evidence (ARCHITECTURE.md "Why no Pallas
kernel on the hot path").

The north star's literal form is a single vmapped Pallas kernel stepping the
key-state table.  Round 1 prototyped the candidates on the target chip and
replaced them with XLA scatter/gather; this script IS those prototypes,
restored in-tree (round-4 verdict weak #5) so the decision is reproducible
on hardware at any time:

  A. ``xla``     — the production formulation: packed-ts scatter-MAX into the
                   (K,) arbiter column + one fused [pts|sst|val] int8 row
                   set-scatter (core/faststep.py:_ts_scatter_max /
                   _winner_row_scatter shapes).
  B. ``serial``  — Pallas kernel, VMEM-resident table block, fori_loop over
                   messages with dynamic-index stores (the only scatter
                   Mosaic supports).
  C. ``onehot``  — exact scatter as an MXU matmul: one-hot(keys) @ rows.
                   Does O(K x M) work for O(M) payload; the sweep over K
                   shows the amplification directly.
  D. ``vgather`` — vectorized dynamic gather (rows = table[keys]) inside a
                   Pallas kernel.  Mosaic rejects the lowering (reported,
                   not timed, if it fails to compile).

Round-5 re-measurement on the chip (PALLAS_PROBE.json; median-of-5 slope
timing, see _time): the XLA pair moves 49,152 messages into the 1M-key
bench table in ~3.7 ms (~0.076 us/msg).  The serial kernel has IMPROVED on
the current Mosaic toolchain (round 1 measured ~10 us/msg; today a
VMEM-block-resident loop runs ~6 ns/iteration and slightly beats XLA at
K=4096 toy shapes) — but it cannot scale to the production table: 1M keys
x 44 B/row = 46 MB >> ~16 MB VMEM, so a full-table serial kernel must grid
over >= 16 table blocks and scan every unsorted message per block
(O(nblk x M) iterations ~= 4.7+ ms before masking costs, above XLA's one
op), or pre-sort messages by block — re-implementing exactly the routing
XLA's scatter already does.  ``onehot`` cannot even materialize its (M, K)
operand at bench shape (48 GB), and ``vgather`` still fails to lower
("Cannot do int indexing on TPU").  The XLA formulation stays.

Since ISSUE 8 every candidate is also run through the static invariant
analyzer (hermes_tpu/analysis — which now interprets pallas_call bodies):
each cell carries ``analysis_clean`` (no error/warn findings under
concrete-seeded bounds) so the mega-round builder knows which candidate
formulations already pass the passes.  ``--annotate`` re-derives ONLY the
analysis fields into an existing PALLAS_PROBE.json, preserving the
on-chip timings (analysis is platform-independent).

Usage (TPU, default env — one process, never kill mid-claim):

    python scripts/pallas_probe.py [--json PALLAS_PROBE.json]
    python scripts/pallas_probe.py --annotate PALLAS_PROBE.json  # CPU ok

On CPU the kernels run interpret=True: functional parity only, timings
meaningless (the cells are tagged with the platform).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

W = 10  # int32 words per table row ([pts | sst | 8 val words], bench shape)


def _time(step, state, args, n_lo=4, n_hi=20):
    """Per-call seconds of ``state -> step(state, *args)``, measured as the
    SLOPE between two in-jit repetition counts — the tunneled runtime's
    per-dispatch floor (~20 ms, see bench.py) would otherwise swamp every
    cell.  The floor also JITTERS ~±10 ms dispatch-to-dispatch, so each
    repetition count is timed as the median of 5 dispatches; pick
    (n_hi - n_lo) * expected-cost well above that jitter.  ``step`` must be
    shape-preserving in ``state``."""

    def reps(n):
        @jax.jit
        def f(state, *args):
            return jax.lax.fori_loop(
                0, n, lambda i, s: step(s, *args), state)

        out = f(state, *args)
        jax.block_until_ready(out)
        jax.device_get(jax.tree.leaves(out)[0])  # force synchronous link
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            out = f(state, *args)
            jax.block_until_ready(out)
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    return (reps(n_hi) - reps(n_lo)) / (n_hi - n_lo)


def _msgs(key, K, M):
    kk, kp = jax.random.split(jax.random.PRNGKey(key))
    keys = jax.random.randint(kk, (M,), 0, K, jnp.int32)
    pts = jax.random.randint(kp, (M,), 1, 1 << 20, jnp.int32)
    rows = jnp.tile(pts[:, None], (1, W))
    return keys, pts, rows


# -- invariant analysis of one candidate step --------------------------------


def analyze_step(fn, args, state_idx=()):
    """Run the jaxpr invariant analyzer (all five passes, kernel bodies
    interpreted) over one candidate step.  Arguments at ``state_idx``
    are resident state seeded dtype-TOP (any reachable content); the
    rest are the probe's message operands, seeded from their concrete
    values.  Returns the ``analysis_*`` cell fields."""
    from hermes_tpu.analysis import domain as D
    from hermes_tpu.analysis import interp as I
    from hermes_tpu.analysis.passes import default_passes
    import numpy as np

    jx = jax.make_jaxpr(fn)(*args)
    avs = [D.top(np.asarray(a).dtype) if i in state_idx
           else D.from_concrete(np.asarray(a))
           for i, a in enumerate(args)]
    ps = default_passes()
    ctx = I.Ctx(passes=ps)
    I.eval_jaxpr(jx.jaxpr, avs, ctx, consts=list(jx.consts))
    fs = [f for p in ps for f in p.results()]
    gating = [f for f in fs if f.severity in ("error", "warn")]
    skipped = [f.message for f in fs if f.code == "pallas-skipped"]
    return dict(
        analysis_clean=not gating,
        analysis_findings=[f"{f.severity}:{f.pass_name}/{f.code}@{f.site}"
                           for f in gating],
        **({"analysis_skipped": skipped} if skipped else {}))


# -- the candidate builders (ONE source for timing cells and --annotate) -----


def candidate_step(cand, K, M, interpret=True):
    """The SAME formulation the timing cells run, shared with
    ``--annotate`` so re-derived analysis fields can never drift from
    the formulation that was timed on chip.  Returns
    ``(fn, args, state_idx)``: the step callable, its concrete
    arguments (each candidate's canonical message seed), and the
    argument indices holding resident state (seeded dtype-TOP for
    analysis; the rest seed from their concrete values)."""
    if cand == "xla":
        keys, pts, rows = _msgs(0, K, M)
        rows8 = jax.lax.bitcast_convert_type(
            rows, jnp.int8).reshape(M, 4 * W)
        vpts = jnp.zeros((K,), jnp.int32)
        bank = jnp.zeros((K, 4 * W), jnp.int8)
        return _xla_step, (vpts, bank, keys, pts, rows8), (0, 1)
    if cand == "serial":
        keys, _pts, rows = _msgs(1, K, M)
        table = jnp.zeros((K, W), jnp.int32)

        def serial_fn(table, keys, rows):
            return pl.pallas_call(
                _serial_kernel,
                in_specs=[
                    pl.BlockSpec(memory_space=pltpu.SMEM),
                    pl.BlockSpec((M, W), lambda: (0, 0)),
                    pl.BlockSpec((K, W), lambda: (0, 0)),
                ],
                out_specs=pl.BlockSpec((K, W), lambda: (0, 0)),
                out_shape=jax.ShapeDtypeStruct((K, W), jnp.int32),
                input_output_aliases={2: 0},
                interpret=interpret,
            )(keys, rows, table)

        return serial_fn, (table, keys, rows), (0,)
    if cand == "onehot":
        keys, _pts, rows = _msgs(2, K, M)
        acc = jnp.zeros((K, W), jnp.int32)

        def onehot_fn(acc, keys, rows):
            onehot = (keys[:, None]
                      == jnp.arange(K, dtype=jnp.int32)[None, :])
            # int8 planes keep the scatter exact through the MXU (bf16
            # would round) for the 0/1 onehot plane; rows mixes in the
            # carry so the loop body is not hoistable.  The payload is
            # masked to the low 7 bits BEFORE the int8 narrow so the
            # convert is value-preserving (round-15: was a bare astype —
            # a silent two's-complement wrap the analyzer truthfully
            # flagged as dtype/implicit-wrap-convert; the mask is one
            # fused elementwise AND, timing-neutral for a cell whose
            # cost is the O(K x M) MXU work).
            rows = (rows + acc[:1, :]) & 0x7F
            return jax.lax.dot_general(
                onehot.astype(jnp.int8), rows.astype(jnp.int8),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)

        return onehot_fn, (acc, keys, rows), (0,)
    if cand == "vgather":
        keys, _pts, _rows = _msgs(3, K, M)
        table = jnp.ones((K, W), jnp.int32)

        def vgather_fn(keys, table):
            out = pl.pallas_call(
                _vgather_kernel,
                out_shape=jax.ShapeDtypeStruct((M, W), jnp.int32),
                interpret=interpret,
            )(keys, table)
            return out[:, 0] & (K - 1)  # feed back as keys (no hoisting)

        return vgather_fn, (keys, table), (1,)
    raise KeyError(cand)


# -- A: the production XLA formulation --------------------------------------


def _xla_step(vpts, bank, keys, pts, rows8):
    vpts = vpts.at[keys].max(pts, mode="drop")
    # mirrors faststep's audited winner-row site: duplicate keys write
    # byte-identical rows in the production round (the probe's random
    # rows don't carry that invariant, but the formulation does)
    from hermes_tpu.core import layouts

    with layouts.audited("winner-row-dup-writes-identical"):
        bank = bank.at[keys].set(rows8, mode="drop")
    return vpts, bank


def cell_xla(K, M, n_lo=200, n_hi=2000):
    fn, args, si = candidate_step("xla", K, M)
    dt = _time(lambda s, k, p, r: fn(*s, k, p, r),
               args[:2], args[2:], n_lo=n_lo, n_hi=n_hi)
    return dict(cand="xla", K=K, M=M, s_per_call=dt, us_per_msg=dt / M * 1e6,
                **analyze_step(fn, args, state_idx=si))


# -- B: serial VMEM apply (Pallas) ------------------------------------------


def _serial_kernel(keys_ref, rows_ref, tin_ref, tout_ref):
    # tout aliases the table input (input_output_aliases), so untouched
    # rows keep their values; the loop applies one message per iteration —
    # the only scatter shape Mosaic accepts (dynamic single-row stores)
    del tin_ref

    def body(i, _):
        k = keys_ref[i]
        tout_ref[pl.dslice(k, 1), :] = rows_ref[pl.dslice(i, 1), :]
        return 0

    jax.lax.fori_loop(0, keys_ref.shape[0], body, 0)


def cell_serial(K, M, interpret, n_lo=100, n_hi=1000):
    fn, args, si = candidate_step("serial", K, M, interpret=interpret)
    dt = _time(fn, args[0], args[1:], n_lo=n_lo, n_hi=n_hi)
    return dict(cand="serial", K=K, M=M, s_per_call=dt,
                us_per_msg=dt / M * 1e6,
                **analyze_step(fn, args, state_idx=si))


# -- C: one-hot MXU scatter --------------------------------------------------


def cell_onehot(K, M):
    fn, args, si = candidate_step("onehot", K, M)
    dt = _time(fn, args[0], args[1:], n_lo=200, n_hi=2000)
    return dict(cand="onehot", K=K, M=M, s_per_call=dt, us_per_msg=dt / M * 1e6,
                flops_amplification=K,
                **analyze_step(fn, args, state_idx=si))


# -- D: vectorized dynamic gather inside Pallas ------------------------------


def _vgather_kernel(keys_ref, table_ref, out_ref):
    out_ref[:] = table_ref[keys_ref[:], :]


def cell_vgather(K, M, interpret):
    f, args, si = candidate_step("vgather", K, M, interpret=interpret)
    keys, table = args
    analysis = analyze_step(f, args, state_idx=si)
    try:
        dt = _time(f, keys, (table,), n_lo=40, n_hi=200)
        return dict(cand="vgather", K=K, M=M, s_per_call=dt,
                    us_per_msg=dt / M * 1e6, compiled=True, **analysis)
    except Exception as e:  # Mosaic lowering rejection is the expected result
        first = str(e).strip().splitlines()
        return dict(cand="vgather", K=K, M=M, compiled=False,
                    error=(first[0] if first else type(e).__name__)[:300],
                    **analysis)


def annotate(path: str) -> None:
    """Re-derive ONLY the ``analysis_*`` fields of an existing probe
    artifact, preserving its on-chip timings (the analyzer is abstract
    and platform-independent; the probe shapes rebuild from each cell's
    recorded K/M with the candidate's canonical message seed)."""
    with open(path) as f:
        doc = json.load(f)
    for cell in doc["cells"]:
        cand, K, M = cell["cand"], cell["K"], cell["M"]
        try:
            fn, args, si = candidate_step(cand, K, M, interpret=True)
        except KeyError:
            continue
        ana = analyze_step(fn, args, state_idx=si)
        cell.pop("analysis_skipped", None)
        cell.update(ana)
        print(json.dumps(dict(cand=cand, K=K, M=M, **ana)),
              file=sys.stderr)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("--annotate", default=None, metavar="PROBE_JSON",
                    help="update analysis_* fields of an existing probe "
                    "artifact in place (timings untouched; CPU-safe)")
    args = ap.parse_args()

    if args.annotate:
        annotate(args.annotate)
        return

    platform = jax.devices()[0].platform
    interpret = platform != "tpu"
    if interpret:
        # CPU smoke is functional parity only; the TPU-sized repetition
        # counts would crawl under interpret mode — shrink them globally
        global _time
        _orig_time = _time

        def _time(step, state, args, n_lo=1, n_hi=3, _t=_orig_time):
            return _t(step, state, args, n_lo=1, n_hi=3)
    cells = []

    # A vs B at the VMEM-resident block shape the serial kernel needs
    # (K=4096 x 10 words fits VMEM); then A alone at the bench table shape.
    for K, M in ((4096, 4096),):
        cells.append(cell_xla(K, M))
        try:
            cells.append(cell_serial(K, M, interpret))
        except Exception as e:
            cells.append(dict(cand="serial", K=K, M=M, compiled=False,
                              error=str(e).strip().splitlines()[0][:300]))
    cells.append(cell_xla(1 << 20, 49152))  # production shape (bench lanes)

    # C: the K-sweep shows the O(K) amplification
    for K in (1024, 4096, 16384):
        cells.append(cell_onehot(K, 4096))

    cells.append(cell_vgather(4096, 4096, interpret))

    out = dict(platform=platform,
               device=getattr(jax.devices()[0], "device_kind", "?"),
               interpret=interpret, cells=cells)
    for c in cells:
        print(json.dumps(c), file=sys.stderr)
    print(json.dumps({k: v for k, v in out.items() if k != "cells"}))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
