"""Sustained deep-chain soak: prove the version-rebase keeps a zipfian
chain-2048 run alive PAST the packed-ts budget on real hardware (round-3
verdict item 4's cliff, removed in round 4).

At chain depth 2048 the hottest key burns ~2048 versions/round, so the
~1M budget's soft watermark (rebase_fraction=0.5 -> ~512k) is crossed in
~250 rounds — the runtime's counter-poll auto-rebase must then quiesce,
reset settled keys to version 1, and let the run continue.  Without the
rebase this run dies with a loud RuntimeError at ~512 rounds.

Usage (chip, default env, ONE process): python scripts/rebase_soak.py
Writes REBASE_SOAK.json: per-poll watermark trajectory + rebase count.
"""

import argparse
import json
import sys
import time

sys.path.insert(0, ".")

import bench  # noqa: E402  (repo-root import; provides _cfg + probe)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--polls", type=int, default=16)
    ap.add_argument("--rounds-per-poll", type=int, default=50)
    ap.add_argument("--out", default="REBASE_SOAK.json")
    ap.add_argument("--metrics-out", default=None, metavar="RUN_JSONL",
                    help="obs run log: stamped per-poll metrics + rebase "
                    "spans (scripts/obs_report.py renders the timeline)")
    args = ap.parse_args()

    # the legacy stdout/stderr contract lines ride the unstamped exporter,
    # byte-identical to the print(json.dumps(...)) they replace
    from hermes_tpu.obs.metrics import JsonlExporter

    out = JsonlExporter(sys.stdout, stamp=False)
    err = JsonlExporter(sys.stderr, stamp=False)

    ok, info = bench.probe_backend(180.0)
    if not ok:
        out.write({"error": info})
        sys.exit(1)

    import jax

    from hermes_tpu.runtime import FastRuntime

    cfg = bench._cfg("zipfian")  # production depth: sort + chain 2048
    rt = FastRuntime(cfg)
    obs = None
    if args.metrics_out:
        from hermes_tpu.obs import Observability

        obs = rt.attach_obs(Observability(path=args.metrics_out))
    # telemetry-only run: skip the per-round completion fetch (tens of MB
    # per round at bench shape through the tunneled link)
    rt.fetch_completions = False
    t0 = time.perf_counter()
    traj = []
    for p in range(args.polls):
        rt.run(args.rounds_per_poll)
        c = rt.counters()  # the poll where auto-rebase triggers
        traj.append(dict(
            poll=p, step=rt.step_idx, max_ver=c["max_ver"],
            rebases=rt.rebases,
            commits=int(c["n_write"] + c["n_rmw"]),
        ))
        err.write(traj[-1])
        if obs is not None:
            obs.interval(traj[-1])
    wall = time.perf_counter() - t0

    total_rounds = args.polls * args.rounds_per_poll
    # exact era-corrected cumulative watermark: per-key reclaimed deltas +
    # that key's CURRENT version, maxed over keys (summing the two maxima
    # independently would overstate it when the hot key shifts)
    import numpy as np

    from hermes_tpu.core import faststep as fst

    cur = np.asarray(jax.device_get(fst.pts_ver(rt.fs.table.vpts)),
                     dtype=np.int64)
    if rt._ver_base is not None:
        cum = int((rt._ver_base + cur[: rt._ver_base.shape[0]]).max())
    else:
        cum = int(cur.max())
    # true high-water marks: the poll-sampled values PLUS the value that
    # triggered each rebase (the peak a poll otherwise never sees)
    peaks = [t["max_ver"] for t in traj] + rt.prerebase_peaks
    summary = dict(
        mix="zipfian", chain_writes=cfg.chain_writes,
        rounds=total_rounds, wall_s=round(wall, 1),
        rebases=rt.rebases,
        prerebase_peaks=rt.prerebase_peaks,
        max_ver_final=traj[-1]["max_ver"],
        cumulative_max_ver=cum,
        budget=cfg.max_key_versions,
        budget_crossed=cum > cfg.max_key_versions,
        watermark_stayed_under_budget=all(
            v < cfg.max_key_versions for v in peaks),
        trajectory=traj,
        platform=jax.devices()[0].platform,
    )
    with open(args.out, "w") as f:
        json.dump(summary, f, indent=1)
    if obs is not None:
        obs.summary({k: v for k, v in summary.items() if k != "trajectory"})
        obs.close()
    out.write({k: v for k, v in summary.items() if k != "trajectory"})
    if not (summary["rebases"] >= 1 and summary["budget_crossed"]
            and summary["watermark_stayed_under_budget"]):
        sys.exit(1)


if __name__ == "__main__":
    main()
