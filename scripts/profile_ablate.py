"""Ablation profiling of the fast round at the BENCH configuration.

Methodology (ARCHITECTURE.md): monkeypatch one phase at a time to a shape-
preserving no-op inside a donated scan chunk, force synchronous mode with a
readback, and attribute the full-vs-ablated difference to the phase.  The
ablated programs compute WRONG protocol results — this is a timing harness
only.  Run:

    python scripts/profile_ablate.py [S] [C] [rounds]
"""

import sys
import time

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp

import bench
from hermes_tpu.core import faststep as fst
from hermes_tpu.core import kernels
from hermes_tpu.workload import ycsb

jax.device_get(jnp.zeros(8) + 1)  # force synchronous (honest) mode

S = int(sys.argv[1]) if len(sys.argv) > 1 else 65536
ROUNDS = int(sys.argv[3]) if len(sys.argv) > 3 else 30

# the EXACT bench configuration (sort arbiter + chaining included), so
# attributions describe the program the bench actually runs; the lane
# budget tracks S at bench._cfg's own 3/4 ratio unless argv pins it
over = dict(n_sessions=S)
if len(sys.argv) > 2:
    over["lane_budget_cfg"] = int(sys.argv[2])
cfg = bench._cfg("a", over=over)
C = cfg.lane_budget


def timed(reps=3):
    fs0 = jax.device_put(fst.init_fast_state(cfg))
    stream = jax.device_put(fst.prep_stream(ycsb.stub_stream(cfg)))
    chunk = fst.build_fast_scan(cfg, ROUNDS, donate=True)
    fs = chunk(fs0, stream, fst.make_fast_ctl(cfg, 0))
    jax.block_until_ready(fs)
    jax.device_get(jax.tree.map(lambda x: x.ravel()[0], fs))
    t0 = time.perf_counter()
    for c in range(1, 1 + reps):
        fs = chunk(fs, stream, fst.make_fast_ctl(cfg, c * ROUNDS))
    jax.block_until_ready(fs)
    jax.device_get(jax.tree.map(lambda x: x.ravel()[0], fs))
    dt = (time.perf_counter() - t0) / reps / ROUNDS * 1e3
    m = jax.device_get(fs.meta)
    commits = int(m.n_write.sum() + m.n_rmw.sum()) / (1 + reps) / ROUNDS
    return dt, commits


orig = {
    "_apply_commit_lanes": fst._apply_commit_lanes,
    "_apply_inv_lanes": fst._apply_inv_lanes,
    "stats_block": kernels.stats_block,
    "sort": jax.lax.sort,
    "_write_value": fst._write_value,
}


def restore():
    fst._apply_commit_lanes = orig["_apply_commit_lanes"]
    fst._apply_inv_lanes = orig["_apply_inv_lanes"]
    kernels.stats_block = orig["stats_block"]
    jax.lax.sort = orig["sort"]
    fst._write_value = orig["_write_value"]


def run(name, patch=None):
    restore()
    if patch:
        patch()
    dt, commits = timed()
    print(f"  {name:28s}: {dt:7.2f} ms/round   ({commits:8.0f} commits/round)")
    restore()
    return dt


base = run("full round")

run("no commit row-scatter", lambda: setattr(
    fst, "_apply_commit_lanes",
    lambda cfg, ctl, fs, lanes, win_lane, commit_lane: fs))

run("no vpts scatter-max", lambda: setattr(
    fst, "_apply_inv_lanes",
    lambda cfg, ctl, fs, lanes, taken_lane: (fs, None)))


def _no_stats():
    from hermes_tpu.core import state as st
    from hermes_tpu.core import types as t

    def fake(step, op, invoke_step, commit, abort, read_done):
        R, Sd = op.shape
        code = jnp.zeros((R, Sd), jnp.int32)
        ctr = jnp.zeros((R, 8), jnp.int32)
        ctr = ctr.at[:, kernels.CTR_WRITE].set(
            jnp.sum((commit & (op == t.OP_WRITE)).astype(jnp.int32), axis=1))
        ctr = ctr.at[:, kernels.CTR_RMW].set(
            jnp.sum((commit & (op == t.OP_RMW)).astype(jnp.int32), axis=1))
        hist = jnp.zeros((R, st.LAT_BINS), jnp.int32)
        return code, ctr, hist
    kernels.stats_block = fake


run("no stats kernel", _no_stats)

# patching lax.sort ablates BOTH sorts of the round under the sort
# arbiter — the issue-arbitration sort and the lane compaction sort —
# so the attribution is their combined cost
run("no sorts (arbiter+compaction)", lambda: setattr(
    jax.lax, "sort", lambda x, dimension=-1, num_keys=1: x))

run("no write-value materialize", lambda: setattr(
    fst, "_write_value",
    lambda cfg, my_cid, op_idx: jnp.zeros(
        op_idx.shape + (cfg.value_words,), jnp.int32)))
