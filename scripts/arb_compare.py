"""Sort-vs-race arbitration (and write chaining) at the exact bench shape
(round-2 verdict item 6): one process, one chip claim, every cell through
bench.run_mix's measurement protocol.

Matrix:
  * mixes a / rmw: arb race vs sort (chaining is a contention lever; the
    uniform mixes measure the arbiter cost difference itself)
  * mix zipfian: race+0, sort+0, sort+chain128 (the round-3 hot-key lever,
    BASELINE.md "Round-3 mitigation")
  * mix a: also sort+chain128, to pin that chaining does not regress the
    primary uncontended metric

Writes ARB_COMPARE.json and prints one JSON line per cell to stderr, plus
a final summary line to stdout.  Run on the real chip (default env, no
other TPU process, no timeout-kill).
"""

import json
import os
import sys
import time

sys.path.insert(0, ".")

import bench

CELLS = [
    ("a", {"arb_mode": "race", "chain_writes": 0}),
    ("a", {"arb_mode": "sort", "chain_writes": 0}),
    ("a", {"arb_mode": "sort", "chain_writes": 128}),
    ("rmw", {"arb_mode": "race", "chain_writes": 0}),
    ("rmw", {"arb_mode": "sort", "chain_writes": 0}),
    ("zipfian", {"arb_mode": "race", "chain_writes": 0}),
    ("zipfian", {"arb_mode": "sort", "chain_writes": 0}),
    ("zipfian", {"arb_mode": "sort", "chain_writes": 128}),
    # the round-4 production depth (bench default: zipfian chain=2048)
    ("zipfian", {"arb_mode": "sort", "chain_writes": 2048}),
]


def main() -> None:
    ok, info = bench.probe_backend(
        float(os.environ.get("HERMES_BENCH_PROBE_TIMEOUT", "180")))
    if not ok:
        print(json.dumps({"error": info}))
        sys.exit(1)

    results = []
    for mix, over in CELLS:
        t0 = time.perf_counter()
        r = bench.run_mix(mix, over=over)
        r["arb"] = over["arb_mode"]
        r["chain_writes"] = over["chain_writes"]
        r["cell_wall_s"] = round(time.perf_counter() - t0, 1)
        results.append(r)
        print(json.dumps(r), file=sys.stderr, flush=True)
        # rewrite after every cell: a mid-matrix chip failure must not
        # discard the completed cells' artifact
        with open("ARB_COMPARE.json", "w") as f:
            json.dump(results, f, indent=1)
    best = {}
    for r in results:
        key = r["mix"]
        if key not in best or r["writes_per_sec"] > best[key]["writes_per_sec"]:
            best[key] = r
    print(json.dumps({
        m: {"arb": b["arb"], "chain_writes": b["chain_writes"],
            "writes_per_sec": b["writes_per_sec"]}
        for m, b in best.items()
    }))


if __name__ == "__main__":
    main()
