"""Phase-level profiling of the batched step (dev tool, not shipped API)."""
import functools, time, sys
import jax, jax.numpy as jnp

from hermes_tpu.config import HermesConfig, WorkloadConfig
from hermes_tpu.core import state as st, step as step_lib, phases
from hermes_tpu.workload import ycsb


def timeit(f, *args, n=20):
    o = f(*args)
    jax.block_until_ready(o)
    t0 = time.perf_counter()
    for _ in range(n):
        o = f(*args)
    jax.block_until_ready(o)
    return (time.perf_counter() - t0) / n * 1e3  # ms


def main(K=1 << 20, S=4096):
    cfg = HermesConfig(
        n_replicas=8, n_keys=K, value_words=8, n_sessions=S, replay_slots=256,
        ops_per_session=128, workload=WorkloadConfig(read_frac=0.5, seed=0),
    )
    r = cfg.n_replicas
    rs = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (r,) + x.shape),
                      st.init_replica_state(cfg))
    rs = jax.device_put(rs)
    stream = jax.device_put(jax.tree.map(jnp.asarray, ycsb.make_streams(cfg)))
    ctl = step_lib.make_ctl(cfg, 0)
    pctl = step_lib._per_replica_ctl(cfg, ctl)
    ph = step_lib.vmapped_phases(cfg)

    full = jax.jit(lambda rs, stream, ctl: step_lib._step_core(
        cfg, ph, step_lib.lockstep_bcast, step_lib.lockstep_route_back,
        step_lib.lockstep_bcast, rs, stream, step_lib._per_replica_ctl(cfg, ctl)))
    print(f"K={K} S={S}  full step: {timeit(full, rs, stream, ctl):8.2f} ms")

    c = jax.jit(lambda: ph["coordinate"](pctl, rs.table, rs.sess, rs.replay, stream))()
    jax.block_until_ready(c)
    print(f"  coordinate : {timeit(jax.jit(lambda rs, stream: ph['coordinate'](pctl, rs.table, rs.sess, rs.replay, stream)), rs, stream):8.2f} ms")

    in_inv = step_lib.lockstep_bcast(c.out_inv)
    f_ai = jax.jit(lambda table, sess, meta, in_inv: ph["apply_inv"](pctl, table, sess, meta, in_inv))
    a = f_ai(c.table, c.sess, rs.meta, in_inv)
    jax.block_until_ready(a)
    print(f"  apply_inv  : {timeit(f_ai, c.table, c.sess, rs.meta, in_inv):8.2f} ms")

    in_ack = step_lib.lockstep_route_back(a.out_ack)
    f_ca = jax.jit(lambda table, sess, replay, meta, in_ack: ph["collect_acks"](pctl, table, sess, replay, meta, in_ack))
    k = f_ca(a.table, a.sess, c.replay, a.meta, in_ack)
    jax.block_until_ready(k)
    print(f"  collect_ack: {timeit(f_ca, a.table, a.sess, c.replay, a.meta, in_ack):8.2f} ms")

    in_val = step_lib.lockstep_bcast(k.out_val)
    f_av = jax.jit(lambda table, in_val: ph["apply_val"](pctl, table, in_val))
    print(f"  apply_val  : {timeit(f_av, k.table, in_val):8.2f} ms")


if __name__ == "__main__":
    K = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
    S = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
    main(K, S)
