"""Benchmark: committed writes/sec of the Hermes protocol round.

Target (BASELINE.json:5): >=10M committed writes/sec aggregate on a v5e-8
(8 replicas, 1 chip = 1 replica).  This environment exposes ONE v5e chip, so
the bench runs the 8-replica configuration batched on that chip — every
replica's protocol work AND all 8x8 message traffic execute on the single
chip.  On a real 8-chip mesh each chip runs the sharded program instead:
identical per-chip apply volume by construction, plus wire routing and ICI
collectives — quantified in SHARDED_CENSUS.json / BASELINE.md "Round-5:
the sharded round, quantified" (projected v5e-8 aggregate ~10.0-13.1M w/s
depending on how the routing delta is priced; the round-1 "lower bound"
framing is retired there).

Runs the TPU-optimized round (core/faststep.py: packed-ts scatter-max
conflict resolution, lane-direct applies, cond-gated replay scan),
scan-chunked so one dispatch executes ROUNDS protocol rounds (SURVEY.md §7
M6).

Workload mixes (BASELINE.json:7-9):
  * ``a``       — YCSB-A 50/50 read/write, uniform (config 1; the primary
                  metric the driver records)
  * ``rmw``     — YCSB-F-shaped write-heavy read-modify-write, uniform
                  (config 2)
  * ``zipfian`` — YCSB-A mix over scrambled Zipfian(0.99) keys (config 3;
                  contended hot keys)
``python bench.py`` prints the primary (YCSB-A) line on stdout — the driver
contract.  ``python bench.py --mix all`` additionally measures the other
mixes, prints one line each to stderr, and writes BENCH_MIXES.json.
``python bench.py --pipeline`` A/Bs the round-8 serving pipeline instead
(sync vs async completion harvest through FastRuntime, bench shape +
latency mode, byte-identical-Meta assertion) and writes
PIPELINE_COMPARE.json.

Measurement protocol for this runtime (measured, see faststep.py header):
execution through the tunneled PJRT link is DEFERRED until the first
device-to-host readback — ``block_until_ready`` alone does not execute the
queued work — and after that first readback the session runs synchronously.
The first counter readback below therefore both drains the warmup chunk and
switches to honest timing for the measured loop.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} with
vs_baseline = value / 1e7 (the north-star aggregate target).
"""

import argparse
import dataclasses
import json
import os
import sys
import time

import jax
import jax.numpy as jnp

ROUNDS = 50  # protocol rounds per dispatch
CHUNKS = 4  # measured dispatches
WARMUP_CHUNKS = 1

MIXES = ("a", "rmw", "zipfian")


def _cfg(mix: str, over: dict | None = None):
    """Bench config for a mix; ``over`` overrides engine knobs (used by
    scripts/arb_compare.py to measure arbitration variants at the exact
    bench shape)."""
    from hermes_tpu.config import HermesConfig, WorkloadConfig

    wl = {
        "a": WorkloadConfig(read_frac=0.5, seed=0),
        "rmw": WorkloadConfig(read_frac=0.5, rmw_frac=1.0, seed=0),
        "zipfian": WorkloadConfig(
            read_frac=0.5, seed=0, distribution="zipfian", zipf_theta=0.99
        ),
    }[mix]
    # Arbiter choice, measured on-chip (ARB_COMPARE.json, round 4): the
    # sort arbiter beats the race arbiter on EVERY mix (11.59 -> 12.87M w/s
    # YCSB-A, 10.45 -> 12.01M RMW — one lax.sort + permutation scatter vs
    # scatter-min + gather, and no false collisions), so it is the bench
    # default everywhere.  Intra-round write chaining (BASELINE.md
    # "Round-3 mitigation") lifts the per-key service rate from n_replicas
    # to n_replicas*chain_writes per round; the round-4 depth sweep
    # (SWEEP4.json/SWEEP4B.json) showed it scaling to chain=2048 on the
    # contended mix — 97k (race) -> 12.5M w/s, bringing zipfian to the
    # UNIFORM mix's rate — and staying free on uniform.  Off for the RMW
    # mix (RMWs never chain).  Version burn at depth: the hottest key
    # burns ~chain_writes versions/round, so a 250-round zipfian bench
    # consumes ~512k of the ~1M packed-ts budget (within one run's
    # budget); sustained runs are reclaimed by the runtime's auto-rebase
    # (config.auto_rebase).
    arb = dict(arb_mode="sort")
    if mix == "a":
        arb["chain_writes"] = 128
    elif mix == "zipfian":
        arb["chain_writes"] = 2048
    elif mix == "rmw":
        # round-5: nacked RMWs retry in place (config.rmw_retries) instead
        # of completing as aborts — same protocol, the abort work converts
        # to commits (round-4 measured 11.4M aborts against 65.9M commits
        # in 200 rounds at this shape); checked on-chip via
        # scripts/checked_bench.py --mix rmw
        arb["rmw_retries"] = 16
    # In-flight ops per replica + compaction budget, per mix: the round-4
    # sweep under the sort arbiter moved the uniform optimum from
    # (32768, 24576) to (65536, 49152) — 12.28 -> 13.19M w/s (98304 gains
    # <1% more for 1.5x the round latency; 131072 rolls off) — while the
    # contended mix PREFERS the smaller shape (its deep chains saturate
    # the hot keys without more sessions; 65536 at chain 1024 measured
    # 3.8M vs 32768's 7.6M).  SWEEP4.json / SWEEP4B.json.
    S = 32768 if mix == "zipfian" else 65536
    kw = dict(
        **arb,
        n_replicas=8,
        n_keys=1 << 20,  # 1M keys (BASELINE.json:7)
        value_words=8,  # 32B values, the reference's typical small-value shape
        n_sessions=S,
        replay_slots=256,
        ops_per_session=256,
        wrap_stream=True,  # stream cycles; write uids stay unique (config.py)
        device_stream=True,  # counter-hash op stream (no stream gathers)
        lane_budget_cfg=(3 * S) // 4,
        read_unroll=2,  # local-read drain depth (reference read batching)
        rebroadcast_every=4,
        replay_scan_every=32,
    )
    kw.update(over or {})
    if "lane_budget_cfg" not in (over or {}):
        # keep the 3/4 lane-budget ratio tracking an overridden n_sessions
        # (an explicit lane_budget_cfg override always wins)
        kw["lane_budget_cfg"] = (3 * kw["n_sessions"]) // 4
    return HermesConfig(workload=wl, **kw)


def commit_latency_fields(hist, step_us: float) -> dict:
    """Commit-latency fields of a throughput cell, honestly labeled
    (round-15 satellite; regression-tested in tests/test_bench_probe.py).
    The device histogram counts commit latency in WHOLE protocol rounds,
    so at throughput shapes the percentiles are legitimately 0 rounds —
    and a microsecond 'estimate' is not derivable from it: ``(p + 1) *
    step_us`` is only an UPPER BOUND on the percentile (1-round histogram
    resolution), and ``step_us`` itself amortizes the per-dispatch link
    handshake over the scan chunk.  BENCH_r05's ``p50_commit_us_est``
    silently echoed the round time as if measured; the fields are now
    ``*_us_ub`` with the bound semantics stated, and the measured
    microsecond p50 lives where it is measurable — ``bench.py --mix
    latency``'s ``device_round_us`` (one round per dispatch, handshake
    cancelled by the slope method)."""
    from hermes_tpu.stats import percentile_from_hist

    p50_rounds = percentile_from_hist(hist, 0.5)
    p99_rounds = percentile_from_hist(hist, 0.99)
    # None on an empty histogram (zero commits) must not crash the bound
    us_ub = lambda p: None if p is None else round((p + 1) * step_us, 1)
    return {
        "p50_commit_rounds": p50_rounds,
        "p99_commit_rounds": p99_rounds,
        "p50_commit_us_ub": us_ub(p50_rounds),
        "p99_commit_us_ub": us_ub(p99_rounds),
        "commit_us_note": (
            "UPPER BOUNDS: the device histogram has 1-round resolution "
            "and round_us amortizes the dispatch handshake — see "
            "bench.py --mix latency (device_round_us) for the measured "
            "per-round commit latency"),
    }


def run_mix(mix: str, over: dict | None = None, rounds: int = ROUNDS,
            chunks: int = CHUNKS, warmup_chunks: int = WARMUP_CHUNKS) -> dict:
    """One measured bench cell.  This is THE cell-runner: the sweep /
    evidence scripts (scripts/arb_compare.py, scripts/chain_scale.py,
    scripts/sweep4.py) call it with ``over`` overriding any HermesConfig
    field, so every artifact measures the exact shape bench.py runs."""
    from hermes_tpu.core import faststep as fst
    from hermes_tpu.workload import ycsb

    cfg = _cfg(mix, over)
    fs = jax.device_put(fst.init_fast_state(cfg))
    stream = jax.device_put(fst.prep_stream(ycsb.stub_stream(cfg)))
    chunk = fst.build_fast_scan(cfg, rounds, donate=True)

    def counters(x):
        # ONE meta fetch per poll (each device_get is a link round trip)
        m = jax.device_get(x.meta)
        # This raw-faststep path has no FastRuntime, hence no auto-rebase:
        # deep chaining burns ~chain_writes versions/round on the hottest
        # key, so a run long enough to cross the packed-ts budget must
        # fail LOUDLY here rather than silently corrupt the Lamport compare
        max_ver = int(m.max_pts.max()) >> fst.PTS_FC_BITS
        if max_ver >= cfg.max_key_versions:
            raise RuntimeError(
                f"bench run crossed the packed-ts budget (key version "
                f"{max_ver} >= {cfg.max_key_versions}): shorten the run or "
                f"lower chain_writes — this raw path has no auto-rebase.  "
                f"The guard only runs at chunk boundaries, so the chunk "
                f"that crossed minted corrupt Lamport compares mid-chunk: "
                f"every counter measured for THAT chunk is invalid, not "
                f"just the post-crossing remainder")
        return (int(m.n_write.sum() + m.n_rmw.sum()),
                int(m.n_abort.sum()), m.lat_hist.sum(axis=0))

    for c in range(warmup_chunks):
        fs = chunk(fs, stream, fst.make_fast_ctl(cfg, c * rounds))
    jax.block_until_ready(fs)
    # drains warmup; switches the link to synchronous mode
    c0, abort0, lat0 = counters(fs)

    t0 = time.perf_counter()
    for c in range(warmup_chunks, warmup_chunks + chunks):
        fs = chunk(fs, stream, fst.make_fast_ctl(cfg, c * rounds))
    jax.block_until_ready(fs)
    t1 = time.perf_counter()

    measure = chunks * rounds
    c1, abort1, lat1 = counters(fs)
    commits = c1 - c0
    wall = t1 - t0
    wps = commits / wall

    # Commit latency in protocol rounds off the device histogram.  At
    # throughput shapes nearly every write commits in the round it
    # issues, so the percentiles are legitimately 0 ROUNDS — but the
    # histogram's resolution is one whole round, and the scan-chunked
    # bench cannot observe sub-round wall time, so a "p50 in
    # microseconds" is NOT derivable here: (p + 1) * round_us is only an
    # UPPER BOUND on the percentile (and round_us itself amortizes the
    # per-dispatch link handshake over ROUNDS rounds).  Round-15
    # honesty fix (BENCH_r05 carried p50_commit_us_est fields that just
    # echoed the round time as if measured): the fields are now *_us_ub
    # with the bound semantics stated, and the real microsecond p50
    # lives where it is measurable — run_latency's device_round_us (one
    # round per dispatch, handshake cancelled by the slope method).
    hist = lat1 - lat0
    step_us = wall / measure * 1e6
    return {
        "mix": mix,
        "writes_per_sec": round(wps, 1),
        "commits": commits,
        "aborts": abort1 - abort0,
        "rounds": measure,
        "wall_s": round(wall, 4),
        "round_us": round(step_us, 1),
        **commit_latency_fields(hist, step_us),
        "platform": jax.devices()[0].platform,
        "device": getattr(jax.devices()[0], "device_kind", "?"),
        "replicas_on_chip": cfg.n_replicas,
        "rounds_per_dispatch": rounds,
        "n_sessions": cfg.n_sessions,
        "lane_budget": cfg.lane_budget,
    }


def _latency_cfg(n_sessions: int = 1024):
    from hermes_tpu.config import HermesConfig, WorkloadConfig

    return HermesConfig(
        n_replicas=8, n_keys=1 << 20, value_words=8, n_sessions=n_sessions,
        replay_slots=64, ops_per_session=256, wrap_stream=True,
        device_stream=True, read_unroll=1, rebroadcast_every=4,
        replay_scan_every=32, workload=WorkloadConfig(read_frac=0.5, seed=0),
    )


def run_latency(n_sessions: int = 1024) -> dict:
    """The latency-optimized operating point (BASELINE.json:2's p50 metric):
    ONE protocol round per dispatch at small scale, so a write commits in
    one round whose wall time IS the commit latency — no scan amortization.
    The BSP design trades latency for throughput; this measures the other
    end of that curve (the throughput mixes above amortize ROUNDS rounds
    per dispatch)."""
    from hermes_tpu.core import faststep as fst
    from hermes_tpu.workload import ycsb

    cfg = _latency_cfg(n_sessions)
    warm, samples = 5, 100
    fs = jax.device_put(fst.init_fast_state(cfg))
    stream = jax.device_put(fst.prep_stream(ycsb.stub_stream(cfg)))
    step = fst.build_fast_batched(cfg, donate=True)
    # pre-place every round's ctl: a per-dispatch host->device transfer
    # would otherwise dominate the measured latency on this tunneled link
    ctls = [jax.device_put(fst.make_fast_ctl(cfg, i))
            for i in range(warm + samples)]

    def one(i):
        nonlocal fs
        t0 = time.perf_counter()
        fs, _comp = step(fs, stream, ctls[i])
        jax.block_until_ready(fs)
        return time.perf_counter() - t0

    for i in range(warm):
        one(i)
    jax.device_get(fs.meta.n_write)  # force synchronous link mode
    times = sorted(one(warm + i) for i in range(samples))
    m = jax.device_get(fs.meta)
    commits = int(m.n_write.sum() + m.n_rmw.sum())
    from hermes_tpu.stats import percentile_nearest_rank
    pctl = lambda q: percentile_nearest_rank(times, q)
    p50 = pctl(0.50)
    p99 = pctl(0.99)

    # DEVICE-TIME per round (round-4 verdict weak #4): the SLOPE between
    # two scan-chunk sizes of the same program — (t_hi - t_lo)/(n_hi -
    # n_lo) cancels the per-dispatch link handshake exactly (dividing by
    # one chunk size would leave floor/rounds ≈ 2-5 ms inside the number);
    # each size is timed as the median of 5 dispatches against the ±10 ms
    # handshake jitter.  A write commits in the round it issues
    # (p50_commit_rounds = 0 at these uncontended scales), so
    # device_round_us IS the p50 commit latency an untunneled deployment
    # would see.
    n_lo, n_hi, dev_reps = 10, 60, 5

    def chunk_med(n):
        chunk = fst.build_fast_scan(cfg, n, donate=True)
        dfs = jax.device_put(fst.init_fast_state(cfg))
        dfs = chunk(dfs, stream, fst.make_fast_ctl(cfg, 0))
        jax.block_until_ready(dfs)
        jax.device_get(dfs.meta.n_write)
        dts = []
        for c in range(1, 1 + dev_reps):
            t0 = time.perf_counter()
            dfs = chunk(dfs, stream, fst.make_fast_ctl(cfg, c * n))
            jax.block_until_ready(dfs)
            dts.append(time.perf_counter() - t0)
        return sorted(dts)[dev_reps // 2]

    device_round_us = (chunk_med(n_hi) - chunk_med(n_lo)) / (n_hi - n_lo) * 1e6

    # Per-dispatch floor of this tunneled runtime: a trivial one-op program
    # dispatched+awaited the same way.  The measured commit latency includes
    # this link handshake on every round; on an untunneled v5e the floor is
    # tens of microseconds, so p50 - floor estimates the program's own
    # latency.  (Kept as context; device_round_us above is the headline.)
    triv = jax.jit(lambda x: x + 1)
    y = jnp.zeros((8,), jnp.int32)
    y = triv(y)
    jax.block_until_ready(y)
    fl = []
    for _ in range(20):
        t0 = time.perf_counter()
        y = triv(y)
        jax.block_until_ready(y)
        fl.append(time.perf_counter() - t0)
    floor = sorted(fl)[len(fl) // 2]

    return {
        "mix": "latency",
        "round_us": round(p50 * 1e6, 1),
        "device_round_us": round(device_round_us, 1),
        "p50_commit_us": round(p50 * 1e6, 1),
        "p99_commit_us": round(p99 * 1e6, 1),
        "dispatch_floor_us": round(floor * 1e6, 1),
        "p50_minus_floor_us": round((p50 - floor) * 1e6, 1),
        "commits_per_round": commits // (warm + samples),
        "n_sessions": cfg.n_sessions,
        "rounds_per_dispatch": 1,
        "note": "device_round_us (headline): slope between 10- and "
                "60-round scan chunks — the program's own round latency, "
                "handshake cancelled; p50_commit_us is the 1-round/"
                "dispatch wall through the tunneled link, floor = its "
                "handshake",
    }


def _runtime_cell(cfg, rounds: int, warmup: int, fetch: bool = True) -> dict:
    """One serving-loop cell: FastRuntime step_once x rounds with the
    completion fetch on (the client-shaped loop the round-8 pipeline
    overlaps) or off (the pure dispatch+device wall — the device span the
    acceptance criterion subtracts).  Returns wall + Meta counters."""
    from hermes_tpu.runtime import FastRuntime

    rt = FastRuntime(cfg)
    rt.fetch_completions = fetch
    for _ in range(warmup):
        rt.step_once()
    rt.flush_pipeline()
    jax.block_until_ready(rt.fs)
    jax.device_get(rt.fs.meta.n_write)  # tunneled link -> synchronous mode
    t0 = time.perf_counter()
    for _ in range(rounds):
        rt.step_once()
    rt.flush_pipeline()
    jax.block_until_ready(rt.fs)
    wall = time.perf_counter() - t0
    m = jax.device_get(rt.fs.meta)
    return {
        "wall_s": round(wall, 4),
        "round_us": round(wall / rounds * 1e6, 1),
        "rounds": rounds,
        "counters": {
            "n_read": int(m.n_read.sum()), "n_write": int(m.n_write.sum()),
            "n_rmw": int(m.n_rmw.sum()), "n_abort": int(m.n_abort.sum()),
            "lat_sum": int(m.lat_sum.sum()), "lat_cnt": int(m.lat_cnt.sum()),
            "lat_hist": m.lat_hist.sum(axis=0).tolist(),
        },
    }


def run_pipeline_compare(depth: int = 4, rounds: int = 40, warmup: int = 8,
                         mix: str = "a", over: dict | None = None) -> dict:
    """A/B the round-8 serving pipeline (PIPELINE_COMPARE.json): the same
    round sequence at bench shape through FastRuntime with completions
    fetched every round — synchronous harvest (pipeline_depth=1, the
    pre-round-8 loop) vs the depth-``depth`` async harvest ring — plus a
    fetchless cell isolating the device span, and the latency operating
    point (1 round/dispatch) where the ring hides the per-dispatch link
    handshake.  Meta counters must be byte-identical between the sync and
    pipelined cells (same rounds, same device program — the ring only
    re-schedules the readback)."""
    base = dict(over or {})
    cells = {}
    for name, d, fetch in (("sync", 1, True), ("pipelined", depth, True),
                           ("device_only", 1, False)):
        cfg = _cfg(mix, dict(base, pipeline_depth=d,
                             donate_state=True))
        cells[name] = _runtime_cell(cfg, rounds, warmup, fetch=fetch)
        cells[name]["pipeline_depth"] = d

    meta_equal = cells["sync"]["counters"] == cells["pipelined"]["counters"]
    dev = cells["device_only"]["wall_s"]
    overhead = lambda c: round(c["wall_s"] - dev, 4)

    # latency operating point: 1 round per dispatch at small scale — the
    # regime where the per-dispatch handshake dominates and the ring's
    # overlap shows up directly in the per-round wall
    lat = {}
    for name, d in (("sync", 1), ("pipelined", depth)):
        cfg = dataclasses.replace(_latency_cfg(1024), pipeline_depth=d)
        lat[name] = _runtime_cell(cfg, max(rounds, 60), warmup)
        lat[name]["pipeline_depth"] = d

    return {
        "mix": mix,
        "pipeline_depth": depth,
        "cells": cells,
        "meta_equal": meta_equal,
        "host_overhead_sync_s": overhead(cells["sync"]),
        "host_overhead_pipelined_s": overhead(cells["pipelined"]),
        "latency": {
            "sync_round_us": lat["sync"]["round_us"],
            "pipelined_round_us": lat["pipelined"]["round_us"],
        },
        "platform": jax.devices()[0].platform,
        "device": getattr(jax.devices()[0], "device_kind", "?"),
        "note": "host_overhead_* = wall - device_only wall at bench shape "
                "with per-round completion fetch; meta_equal pins the "
                "sync<->pipelined state identity (byte-identical Meta)",
    }


def run_fleet_bench(groups: int = 4, rounds: int | None = None,
                    chunks: int = 2) -> dict:
    """Fleet scale-out cells (round-13, BENCH_FLEET.json): per-group +
    aggregate committed writes/s of a ``groups``-group key-sharded fleet
    (hermes_tpu.fleet.bench.run_fleet_cells), plus the single-group
    baseline and the concurrent-dispatch cell.

    Shape honesty: on a TPU the per-group shape IS the bench shape (the
    YCSB-A ``_cfg('a')`` cell — each group would own its chips on the
    (groups, replicas) grid).  On the host backend the full shape is
    hours of CPU, so the cells run a reduced per-group shape (recorded in
    the artifact) and the JSON carries ``tpu_pending`` naming the on-chip
    rerun — the same carried-over protocol as PIPELINE_COMPARE /
    CHAOS_BENCH / FUSED_COMPARE."""
    from hermes_tpu.config import FleetConfig
    from hermes_tpu.fleet.bench import run_fleet_cells

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        base = _cfg("a")
        rounds = ROUNDS if rounds is None else rounds
    else:
        base = _cfg("a", dict(n_keys=1 << 14, n_sessions=1024,
                              replay_slots=64, lane_budget_cfg=768,
                              chain_writes=128))
        rounds = 10 if rounds is None else rounds
    r = run_fleet_cells(FleetConfig(groups=groups, base=base),
                        rounds=rounds, chunks=chunks)
    r["note"] = (
        "aggregate = sum of per-group cells, each measured alone — the "
        "scale-out capacity when every group owns its devices (exactly "
        "the on-chip deployment); 'concurrent' is the same groups "
        "timesharing THIS host's cores")
    if not on_tpu:
        r["tpu_pending"] = (
            "host-backend stand-in at reduced per-group shape — rerun "
            "bench.py --fleet on the chip for the full bench-shape "
            "cells, alongside the carried-over PIPELINE_COMPARE.json / "
            "CHAOS_BENCH.json / FUSED_COMPARE.json artifacts")
    return r


def _read_bench_cfg(on_tpu: bool):
    """The read-bench store shape: full bench scale on a chip, a
    reduced-but-same-mechanism shape on the host backend (the
    run_fleet_bench carried-over protocol)."""
    from hermes_tpu.config import HermesConfig, WorkloadConfig

    if on_tpu:
        kw = dict(n_keys=1 << 20, n_sessions=8192, n_replicas=8)
    else:
        kw = dict(n_keys=1 << 14, n_sessions=512, n_replicas=4)
    return HermesConfig(
        value_words=8, replay_slots=64, ops_per_session=256,
        pipeline_depth=2, rebroadcast_every=4, replay_scan_every=32,
        workload=WorkloadConfig(read_frac=0.5, seed=0), **kw)


def run_read_bench(n: int | None = None, seed: int = 14) -> dict:
    """Round-16 read-side cells (BENCH_READS.json): the local-read fast
    path measured against the per-op round path it replaces, plus the
    YCSB-B/C/D read-heavy mixes and a checker-gated cell.

      * ``per_op_get``   — N single gets through the classic future path
                           (one key per (replica, session) lane per
                           round) — the baseline the ISSUE's >= 5x
                           acceptance compares against;
      * ``multi_get``    — the same read volume through the batched
                           device-resident path (one gather dispatch per
                           chunk);
      * ``scan``         — full-range scans through the zero-sparse-op
                           slice program;
      * ``ycsb_b/c/d``   — read-heavy mixes (workload.ycsb.READ_MIXES):
                           writes ride submit_batch, reads ride
                           multi_get, interleaved per chunk so D's
                           latest-distribution reads actually chase the
                           write frontier;
      * ``checked``      — a smaller recorded run: linearizability
                           checker green AND stale_read == [] (the read
                           path is verified, not assumed).

    The headline is ``reads_per_sec`` (batched multi_get) with
    ``speedup_x`` vs the per-op rate."""
    import numpy as np

    from hermes_tpu.checker import linearizability as lin
    from hermes_tpu.kvs import KVS
    from hermes_tpu.workload.openloop import MixSpec, make_mix
    from hermes_tpu.workload.ycsb import READ_MIXES

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = _read_bench_cfg(on_tpu)
    if n is None:
        n = 1 << 18 if on_tpu else 1 << 15
    rng = np.random.default_rng(seed)
    kvs = KVS(cfg)
    lanes = [(r, s) for r in range(cfg.n_replicas)
             for s in range(cfg.n_sessions)]

    # preload a write working set so reads observe real values
    prekeys = rng.permutation(cfg.n_keys)[: cfg.n_keys // 2].astype(np.int64)
    vals = rng.integers(1, 1 << 20, size=(prekeys.size, cfg.value_words - 2)
                        ).astype(np.int32)
    bf = kvs.submit_batch(np.full(prekeys.size, KVS.PUT, np.int32), prekeys,
                          vals)
    assert kvs.run_batch(bf), "read-bench preload did not drain"

    read_keys = prekeys[rng.integers(0, prekeys.size, size=n)]

    # warm every measured program OUT of the timed windows (the standard
    # warmup-chunk protocol of run_mix): the read programs compile on
    # first dispatch, and a cold compile inside a cell would swamp the
    # measured rate at host scale
    chunk = 8192
    kvs.multi_get(read_keys[:chunk])
    kvs.scan(0, cfg.n_keys)
    fw = kvs.get(0, 0, int(read_keys[0]))
    assert kvs.run_until([fw])

    # cell 1: the per-op round path (the pre-round-16 get)
    n_per_op = min(n, 2048 if not on_tpu else 16384)
    t0 = time.perf_counter()
    futs = []
    for i in range(n_per_op):
        r, s = lanes[i % len(lanes)]
        futs.append(kvs.get(r, s, int(read_keys[i])))
    assert kvs.run_until(futs), "per-op gets did not drain"
    per_op_wall = time.perf_counter() - t0
    per_op_rate = n_per_op / per_op_wall

    # cell 2: the batched device-resident path (the headline)
    t0 = time.perf_counter()
    local = 0
    for lo in range(0, n, chunk):
        res = kvs.multi_get(read_keys[lo: lo + chunk])
        assert res.all_done()
        local += res.local_served
    mget_wall = time.perf_counter() - t0
    mget_rate = n / mget_wall

    # cell 3: range scans (whole table per dispatch window)
    scan_reps = 4 if not on_tpu else 16
    t0 = time.perf_counter()
    for _ in range(scan_reps):
        res = kvs.scan(0, cfg.n_keys)
        assert res.all_done()
    scan_wall = time.perf_counter() - t0
    scan_rate = scan_reps * cfg.n_keys / scan_wall

    cells = {
        "per_op_get": dict(ops=n_per_op, wall_s=round(per_op_wall, 4),
                           reads_per_sec=round(per_op_rate, 1)),
        "multi_get": dict(ops=n, wall_s=round(mget_wall, 4),
                          reads_per_sec=round(mget_rate, 1),
                          local_served=local, chunk=chunk,
                          fallbacks=kvs.read_stats()["fallback_reads"]),
        "scan": dict(keys=scan_reps * cfg.n_keys,
                     wall_s=round(scan_wall, 4),
                     reads_per_sec=round(scan_rate, 1)),
    }

    # YCSB-B/C/D mixed cells: writes through submit_batch, reads through
    # multi_get, interleaved chunk-wise
    n_mix = min(n, 1 << 14) if not on_tpu else n
    for name, kw in READ_MIXES.items():
        spec = MixSpec(name=f"ycsb_{name}", tenants=4, **kw)
        mix = make_mix(spec, cfg.n_keys, n_mix, seed,
                       value_words=cfg.value_words - 2)
        t0 = time.perf_counter()
        reads = writes = 0
        for lo in range(0, n_mix, chunk):
            kk = mix["key"][lo: lo + chunk]
            kd = mix["kind"][lo: lo + chunk]
            wr = kd != 0
            if wr.any():
                b = kvs.submit_batch(
                    np.full(int(wr.sum()), KVS.PUT, np.int32), kk[wr],
                    mix["value"][lo: lo + chunk][wr])
                assert kvs.run_batch(b)
                writes += int(wr.sum())
            rd = ~wr
            if rd.any():
                res = kvs.multi_get(kk[rd])
                assert res.all_done()
                reads += int(rd.sum())
        wall = time.perf_counter() - t0
        cells[f"ycsb_{name}"] = dict(
            ops=n_mix, reads=reads, writes=writes,
            wall_s=round(wall, 4),
            ops_per_sec=round(n_mix / wall, 1),
            reads_per_sec=round(reads / wall, 1) if reads else 0.0,
            read_frac=spec.read_frac, distribution=spec.distribution)

    # checked cell: the fast path VERIFIED — full linearizability plus
    # the structural stale-read check over a recorded B-mix run
    ccfg = _read_bench_cfg(False)
    ckvs = KVS(ccfg, record="array")
    spec = MixSpec(name="ycsb_b", tenants=4, **READ_MIXES["b"])
    n_chk = 6000
    mix = make_mix(spec, ccfg.n_keys, n_chk, seed,
                   value_words=ccfg.value_words - 2)
    for lo in range(0, n_chk, 1024):
        kk = mix["key"][lo: lo + 1024]
        kd = mix["kind"][lo: lo + 1024]
        wr = kd != 0
        if wr.any():
            b = ckvs.submit_batch(np.full(int(wr.sum()), KVS.PUT, np.int32),
                                  kk[wr], mix["value"][lo: lo + 1024][wr])
            assert ckvs.run_batch(b)
        if (~wr).any():
            assert ckvs.multi_get(kk[~wr]).all_done()
    v = ckvs.rt.check()
    stale = lin.stale_read(ckvs.rt.history_ops())
    cells["checked"] = dict(
        ops=n_chk, checker_ok=bool(v.ok), keys_checked=v.keys_checked,
        stale_read=[repr(e) for e in stale[:4]],
        read_stats=ckvs.read_stats())

    speedup = mget_rate / per_op_rate
    out = {
        "cells": cells,
        "reads_per_sec": cells["multi_get"]["reads_per_sec"],
        "speedup_x": round(speedup, 2),
        "speedup_floor": 5.0,
        "checker_ok": cells["checked"]["checker_ok"],
        "stale_read_clean": not stale,
        "platform": jax.devices()[0].platform,
        "device": getattr(jax.devices()[0], "device_kind", "?"),
        "shape": dict(n_keys=cfg.n_keys, n_sessions=cfg.n_sessions,
                      n_replicas=cfg.n_replicas,
                      value_words=cfg.value_words),
        "seed": seed,
        "note": ("reads_per_sec = batched device-resident multi_get "
                 "(core/readpath.py, one gather dispatch per chunk); "
                 "speedup_x vs the per-op future path; checker cell "
                 "gates full linearizability + stale_read == []"),
    }
    if not on_tpu:
        out["tpu_pending"] = (
            "host-backend stand-in at reduced shape — rerun bench.py "
            "--reads on the chip for the full-scale cells")
    return out


def _values_bench_cfg(on_tpu: bool, max_value_bytes: int = 1024):
    """Round-17 value-heap bench shape: enough keys to stress the log,
    depth-2 pipelining, 1 KB max values against an 8 MiB-capped heap
    (the declared layouts.HEAP_REF reach)."""
    from hermes_tpu.config import HermesConfig, WorkloadConfig

    kw = dict(n_keys=1 << 12, n_sessions=256, n_replicas=3)
    if on_tpu:
        kw = dict(n_keys=1 << 14, n_sessions=512, n_replicas=4)
    return HermesConfig(
        value_words=3, replay_slots=64, ops_per_session=256,
        pipeline_depth=2, max_value_bytes=max_value_bytes,
        heap_bytes=1 << 22,
        workload=WorkloadConfig(read_frac=0.5, seed=0), **kw)


def run_values_bench(n: int | None = None, seed: int = 17) -> dict:
    """Round-17 value-heap cells (BENCH_VALUES.json): GB/s beside
    writes/s — the memcached-shaped claims made measurable.

      * ``put_bytes``     — N variable-length puts (ycsb.value_sizes
                            memcached-shaped draw) through submit_batch:
                            writes/s AND committed GB/s;
      * ``get_bytes``     — the same keys back through the batched
                            local-read path + mirror resolution: reads/s
                            and served GB/s;
      * ``device_gather`` — the raw HBM extent-gather program over the
                            written refs (ONE gather per dispatch —
                            OP_BUDGET heap_path): device-path GB/s;
      * ``scan_bytes``    — full-range scans with payload resolution;
      * ``gc``            — overwrite churn against a SMALL heap: GC
                            count, reclaimed bytes, post-compaction
                            utilization (live/used) — the bounded-heap
                            proof;
      * ``values_ok``     — spot byte-exact round-trip of sampled ops
                            against the derived expected payloads (a
                            FAIL gates the exit code; correctness truth
                            at depth lives in scripts/check_heap.py).
    """
    import numpy as np

    from hermes_tpu.config import HermesConfig
    from hermes_tpu.kvs import KVS
    from hermes_tpu.workload.ycsb import value_payload, value_sizes

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = _values_bench_cfg(on_tpu)
    if n is None:
        n = 1 << 16 if on_tpu else 1 << 13
    rng = np.random.default_rng(seed)
    kvs = KVS(cfg)

    # keys are UNIQUE within each put chunk (a per-chunk sample without
    # replacement): same-key writes inside one batch commit in arbiter
    # order, not submission order, so the byte-exactness spot check needs
    # "last chunk that wrote the key" to name ONE deterministic winner
    chunk = 4096
    keys = np.concatenate([
        rng.permutation(cfg.n_keys)[: min(chunk, n - lo)]
        for lo in range(0, n, chunk)]).astype(np.int64)
    vlen = value_sizes(dict(n=n, max_bytes=cfg.max_value_bytes), seed)
    payloads = [value_payload(seed, i, int(vlen[i])) for i in range(n)]
    total_bytes = int(vlen.sum())

    # warm the compiled programs out of the timed windows
    warm = kvs.submit_batch(np.full(64, KVS.PUT, np.int32), keys[:64],
                            payloads[:64])
    assert kvs.run_batch(warm)
    kvs.multi_get(keys[:chunk])
    kvs.scan(0, cfg.n_keys)

    # cell 1: variable-length puts (writes/s + GB/s)
    t0 = time.perf_counter()
    for lo in range(0, n, chunk):
        bf = kvs.submit_batch(
            np.full(min(chunk, n - lo), KVS.PUT, np.int32),
            keys[lo: lo + chunk], payloads[lo: lo + chunk])
        assert kvs.run_batch(bf), "value puts did not drain"
    put_wall = time.perf_counter() - t0

    # cell 2: batched local reads with payload resolution (reads/s + GB/s)
    t0 = time.perf_counter()
    read_bytes = 0
    for lo in range(0, n, chunk):
        res = kvs.multi_get(keys[lo: lo + chunk])
        assert res.all_done()
        read_bytes += sum(len(d) for d in res.data if d is not None)
    get_wall = time.perf_counter() - t0

    # cell 3: the raw device extent gather over the live refs
    live = kvs.multi_get(np.unique(keys))
    assert live.all_done(), "live-ref read did not serve locally"
    refs = np.asarray(live.value)[:, 0]
    refs = refs[refs != 0].astype(np.int32)
    reps = 8 if on_tpu else 2
    kvs.heap.device_gather(refs[: min(1024, refs.size)])  # warm/compile
    t0 = time.perf_counter()
    dev_bytes = 0
    for _ in range(reps):
        for lo in range(0, refs.size, chunk):
            _rows, lens = kvs.heap.device_gather(refs[lo: lo + chunk])
            dev_bytes += int(lens.sum())
    dev_wall = time.perf_counter() - t0

    # cell 4: range scans with payload resolution
    scan_reps = 4 if not on_tpu else 16
    t0 = time.perf_counter()
    scan_bytes = 0
    for _ in range(scan_reps):
        res = kvs.scan(0, cfg.n_keys)
        assert res.all_done()
        scan_bytes += sum(len(d) for d in res.data if d is not None)
    scan_wall = time.perf_counter() - t0

    # cell 5: GC under overwrite churn against a small heap
    import dataclasses as _dc

    # small enough to force several compactions over the churn, with
    # headroom for the worst-case live set (64 keys x 1 KiB max)
    gcfg = _dc.replace(cfg, n_keys=256, n_sessions=64,
                       heap_bytes=1 << 17)
    gkvs = KVS(gcfg)
    n_churn = 4096
    gkeys = rng.integers(0, 64, size=n_churn).astype(np.int64)
    glens = value_sizes(dict(n=n_churn, max_bytes=gcfg.max_value_bytes),
                        seed + 1)
    t0 = time.perf_counter()
    for lo in range(0, n_churn, 512):
        bf = gkvs.submit_batch(
            np.full(min(512, n_churn - lo), KVS.PUT, np.int32),
            gkeys[lo: lo + 512],
            [value_payload(seed + 1, lo + j, int(glens[lo + j]))
             for j in range(min(512, n_churn - lo))])
        assert gkvs.run_batch(bf)
    gc_wall = time.perf_counter() - t0
    gkvs.heap_gc(reason="bench")
    gstats = gkvs.heap.stats()

    # spot byte-exactness: latest write per key must read back verbatim
    last_of = {}
    for i in range(n):
        last_of[int(keys[i])] = i
    sample = rng.choice(np.asarray(list(last_of.keys())),
                        size=min(256, len(last_of)), replace=False)
    res = kvs.multi_get(sample.astype(np.int64))
    assert res.all_done(), "spot-check read did not serve locally"
    values_ok = all(
        res.data[j] == payloads[last_of[int(sample[j])]]
        for j in range(sample.size))

    gb = 1 << 30
    cells = {
        "put_bytes": dict(
            ops=n, bytes=total_bytes, wall_s=round(put_wall, 4),
            writes_per_sec=round(n / put_wall, 1),
            gb_per_sec=round(total_bytes / put_wall / gb, 4)),
        "get_bytes": dict(
            ops=n, bytes=read_bytes, wall_s=round(get_wall, 4),
            reads_per_sec=round(n / get_wall, 1),
            gb_per_sec=round(read_bytes / get_wall / gb, 4)),
        "device_gather": dict(
            refs=int(refs.size) * reps, bytes=dev_bytes,
            wall_s=round(dev_wall, 4),
            gb_per_sec=round(dev_bytes / dev_wall / gb, 4)),
        "scan_bytes": dict(
            keys=scan_reps * cfg.n_keys, bytes=scan_bytes,
            wall_s=round(scan_wall, 4),
            gb_per_sec=round(scan_bytes / scan_wall / gb, 4)),
        "gc": dict(
            churn_ops=n_churn, wall_s=round(gc_wall, 4),
            gc_runs=gstats["gc_runs"],
            reclaimed_bytes=gstats["gc_reclaimed_bytes"],
            live_bytes=gstats["live_bytes"],
            post_gc_util=round(gstats["util"], 4) if gstats["util"] else None,
            heap_bytes=gcfg.heap_bytes),
    }
    out = {
        "cells": cells,
        "writes_per_sec": cells["put_bytes"]["writes_per_sec"],
        "put_gb_per_sec": cells["put_bytes"]["gb_per_sec"],
        "read_gb_per_sec": cells["get_bytes"]["gb_per_sec"],
        "device_gb_per_sec": cells["device_gather"]["gb_per_sec"],
        "values_ok": bool(values_ok),
        "value_size_classes": dict(
            max_value_bytes=cfg.max_value_bytes,
            mean_bytes=round(float(vlen.mean()), 1)),
        "heap": kvs.heap.stats(),
        "platform": jax.devices()[0].platform,
        "device": getattr(jax.devices()[0], "device_kind", "?"),
        "shape": dict(n_keys=cfg.n_keys, n_sessions=cfg.n_sessions,
                      n_replicas=cfg.n_replicas,
                      heap_bytes=cfg.heap_bytes,
                      max_value_bytes=cfg.max_value_bytes),
        "seed": seed,
        "note": ("round-17 value heap: GB/s beside writes/s — puts land "
                 "extents before the INV issues (round census unchanged), "
                 "reads resolve refs through the mirror, device_gather is "
                 "the raw HBM log path (heap_path budget: ONE gather)"),
    }
    if not on_tpu:
        out["tpu_pending"] = (
            "host-backend stand-in at reduced shape — rerun bench.py "
            "--values on the chip for the full-scale GB/s cells")
    return out


def run_chaos_soak(seed: int, rounds: int = 120, depth: int = 2,
                   warmup: int = 8) -> dict:
    """Serving rate under chaos (round-9, CHAOS_BENCH.json): the bench-
    shape YCSB-A config at pipeline depth ``depth`` with the failure
    detector attached, driven clean vs under a seeded fault schedule
    (freeze/thaw/join/crash-restart/heartbeat-skew; hermes_tpu.chaos) —
    what the composed fault load costs the serving loop.  Round-10: the
    chaos cell additionally samples per-window commit rates
    (hermes_tpu.elastic.RateSampler) and reports the WORST window against
    the clean cell's rate as ``dip_pct`` — the bounded-degradation number
    elastic drills gate on (a fault schedule that merely lowers the
    average can still hide a window of zero service; the worst window
    can't).  Correctness truth lives in scripts/check_chaos.py /
    check_elastic.py and the checker-gated tests; this cell measures rate
    and detection activity."""
    from hermes_tpu import chaos as chaos_lib
    from hermes_tpu.elastic import RateSampler
    from hermes_tpu.membership import MembershipService
    from hermes_tpu.runtime import FastRuntime

    window = max(4, rounds // 8)
    cells = {}
    # round-11 third cell: the same serving loop under deterministic
    # PARTITION+HEAL cycles (asymmetric outbound blackouts driven through
    # the detector oracle — membership.sever; heal rejoins epoch-fenced)
    # — what LOSING AND REGAINING replicas to the network costs, vs the
    # crash/freeze mix.  Deterministic cycles, not seeded draws: a random
    # partition with no recovery path just shrinks the cluster for the
    # rest of the run, and the dip stops being comparable across seeds.
    for name in ("clean", "chaos", "partition"):
        cfg = _cfg("a", dict(pipeline_depth=depth))
        rt = FastRuntime(cfg)
        rt.attach_membership(MembershipService(cfg, confirm_steps=4))
        rt.run(warmup)
        rt.counters()  # close the deferred-execution window before timing
        if name == "chaos":
            sched = chaos_lib.Schedule.random(cfg, seed, rounds)
        elif name == "partition":
            sched = chaos_lib.Schedule.partition_drill(cfg, rounds)
        else:
            sched = chaos_lib.Schedule([])
        # BOTH cells carry the sampler: its per-window counters() sync is
        # part of the measured wall, so the clean-vs-chaos comparison
        # stays apples-to-apples (only the chaos cell's windows are
        # reported)
        sampler = RateSampler(rt, window)
        runner = chaos_lib.ChaosRunner(rt, sched, on_step=sampler.note)
        c0 = rt.counters()
        t0 = time.perf_counter()
        runner.run(rounds, heal=False)
        c1 = rt.counters()  # device sync closes the timing window
        wall = time.perf_counter() - t0
        cells[name] = dict(
            rounds=rounds, wall_s=round(wall, 4),
            round_us=round(1e6 * wall / rounds, 1),
            writes=int(c1["n_write"] + c1["n_rmw"]
                       - c0["n_write"] - c0["n_rmw"]),
            events_applied=len(runner.log),
            membership_events=len(rt.membership.events),
            lost_ops=runner.lost_ops,
        )
        cells[name]["writes_per_sec"] = round(
            cells[name]["writes"] / max(1e-9, wall), 1)
        if name != "clean":
            sampler.finish()
            cells[name]["event_log"] = runner.log
            cells[name]["worst_window"] = sampler.report(
                clean_rate=cells["clean"]["writes_per_sec"])
    return {
        "seed": seed, "pipeline_depth": depth, "cells": cells,
        "slowdown": round(cells["chaos"]["round_us"]
                          / max(1e-9, cells["clean"]["round_us"]), 3),
        "dip_pct": cells["chaos"]["worst_window"]["dip_pct"],
        "partition_dip_pct": cells["partition"]["worst_window"]["dip_pct"],
        "platform": jax.devices()[0].platform,
        "device": getattr(jax.devices()[0], "device_kind", "?"),
        "note": "rate cells only (dip_pct = worst chaos window vs clean "
                "rate; partition cell = detector-oracle asymmetric "
                "blackouts); linearizability under the same fault classes "
                "is gated by scripts/check_chaos.py / check_elastic.py / "
                "check_netchaos.py",
    }


# Shared with __graft_entry__.entry(): every driver entry path fails fast
# on a wedged backend with the same bounded subprocess probe.
from hermes_tpu.probe import probe_backend  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mix", choices=MIXES + ("all", "latency"), default="a")
    ap.add_argument("--metrics-out", default=None, metavar="RUN_JSONL",
                    help="additionally write every measured cell to an obs "
                    "run log (stamped t/kind schema; scripts/obs_report.py)")
    ap.add_argument("--profile-out", default=None, metavar="PROFILE_JSONL",
                    help="additionally write each measured mix's op census "
                    "+ cost-model pricing + measured round time as obs "
                    "profile records (hermes_tpu.obs.profile; abstract "
                    "lowering, no extra device work)")
    ap.add_argument("--analyze", default=None, metavar="FINDINGS_JSONL",
                    help="additionally run the static jaxpr invariant "
                    "analyzer (hermes_tpu.analysis) on each measured mix's "
                    "round program and write the findings as obs analysis "
                    "records (abstract tracing, no extra device work)")
    ap.add_argument("--pipeline", action="store_true",
                    help="A/B the round-8 serving pipeline instead of the "
                    "throughput mixes: sync vs pipelined completion harvest "
                    "at bench shape + latency mode, asserting byte-identical"
                    " Meta counters; writes PIPELINE_COMPARE.json")
    ap.add_argument("--pipeline-depth", type=int, default=4,
                    help="harvest-ring depth for the pipelined cells")
    ap.add_argument("--pipeline-rounds", type=int, default=40,
                    help="measured serving rounds per --pipeline cell")
    ap.add_argument("--chaos", type=int, default=None, metavar="SEED",
                    help="measure serving rate under a seeded chaos "
                    "schedule vs clean (round-9, hermes_tpu.chaos; "
                    "detector attached, --pipeline-depth/-rounds apply); "
                    "writes CHAOS_BENCH.json")
    ap.add_argument("--serve", action="store_true",
                    help="measure the round-14 serving front-end instead: "
                    "end-to-end p50/p99 FROM THE CLIENT SOCKET (framed "
                    "RPC over localhost TCP) for the latency operating "
                    "point (small dispatches, pipeline_depth>=2, donated "
                    "state) and the windowed closed-loop throughput "
                    "point, plus the uniform/zipfian/hot-key scenario "
                    "matrix; writes BENCH_LATENCY.json (host cells carry "
                    "a tpu_pending note)")
    ap.add_argument("--serve-ops", type=int, default=None,
                    help="ops per --serve cell (default: platform-sized)")
    ap.add_argument("--reads", action="store_true",
                    help="measure the round-16 read side instead: batched "
                    "device-resident multi_get vs the per-op get path "
                    "(>=5x acceptance floor), range scans, the YCSB-B/C/D "
                    "read-heavy mixes, and a checker-gated cell with "
                    "stale_read == []; writes BENCH_READS.json (host "
                    "cells carry a tpu_pending note)")
    ap.add_argument("--values", action="store_true",
                    help="measure the round-17 value-heap cells instead of "
                    "the throughput mixes: variable-length put/get GB/s "
                    "beside writes/s, the raw HBM extent-gather path, and "
                    "GC-under-churn utilization; writes BENCH_VALUES.json "
                    "and exits non-zero unless the sampled round trips are "
                    "byte-exact")
    ap.add_argument("--values-ops", type=int, default=None,
                    help="op count for --values (default: platform-scaled)")
    ap.add_argument("--reads-ops", type=int, default=None,
                    help="read volume per --reads cell (default: "
                    "platform-sized)")
    ap.add_argument("--fleet", action="store_true",
                    help="measure the key-sharded fleet instead "
                    "(round-13, hermes_tpu.fleet): per-group + aggregate "
                    "+ concurrent committed-writes/s cells and the "
                    "single-group baseline; writes BENCH_FLEET.json "
                    "(host backend runs a reduced per-group shape with a "
                    "tpu_pending note)")
    ap.add_argument("--fleet-groups", type=int, default=4,
                    help="fleet group count for --fleet")
    ap.add_argument("--probe-timeout", type=float, default=float(
        os.environ.get("HERMES_BENCH_PROBE_TIMEOUT", "180")))
    args = ap.parse_args()

    # Legacy contract lines ride the unstamped exporter — byte-identical to
    # the print(json.dumps(...)) they replace (the BENCH harness scrapes
    # stdout); --metrics-out adds the stamped obs run log alongside.
    from hermes_tpu.obs.metrics import JsonlExporter

    out = JsonlExporter(sys.stdout, stamp=False)
    err = JsonlExporter(sys.stderr, stamp=False)
    obs_exp = (JsonlExporter(open(args.metrics_out, "w"), stamp=True)
               if args.metrics_out else None)

    def cell(rec: dict) -> None:
        if obs_exp is not None:
            obs_exp.write(rec, kind="summary")

    if args.pipeline and args.mix == "latency":
        ap.error("--pipeline already includes the latency cell; pick a "
                 "throughput mix for the bench-shape cells")

    ok, info = probe_backend(args.probe_timeout)
    if not ok:
        # one diagnosable JSON line + non-zero rc instead of inheriting
        # whatever the wedged claim does (the driver contract under outage);
        # latency mode keeps its own record shape so a latency outage can't
        # be misfiled as a zero throughput sample
        rec = ({"mix": "latency", "error": info}
               if args.mix == "latency" else
               {"metric": "committed_writes_per_sec", "value": 0.0,
                "unit": "writes/s", "vs_baseline": 0.0, "error": info})
        out.write(rec)
        sys.exit(1)

    if args.serve:
        from hermes_tpu.serving.bench import run_serve_bench

        r = run_serve_bench(n=args.serve_ops)
        with open("BENCH_LATENCY.json", "w") as f:
            json.dump(r, f, indent=1)
        cell(r)
        lat, thr = r["cells"]["latency"], r["cells"]["throughput"]
        errs = r.get("errors")
        out.write({
            "metric": "serve_latency_p50_us",
            "value": lat["p50_us"],
            "p99_us": lat["p99_us"],
            "throughput_ops_per_sec": thr["ops_per_sec"],
            "throughput_p50_us": thr["p50_us"],
            "dispatch_loop_p50_ms": r["dispatch_loop_p50_ms"],
            "improves_dispatch_loop": r["latency_p50_improves"],
            **({"errors": errs} if errs else {}),
        })
        # a cell that lost its server or part of its answers is NOT a
        # pass, however good the answered-prefix percentiles look
        if errs or not r["latency_p50_improves"]:
            sys.exit(1)
        return

    if args.reads:
        r = run_read_bench(n=args.reads_ops)
        with open("BENCH_READS.json", "w") as f:
            json.dump(r, f, indent=1)
        cell(r)
        out.write({
            "metric": "local_reads_per_sec",
            "value": r["reads_per_sec"],
            "unit": "reads/s",
            "per_op_reads_per_sec":
                r["cells"]["per_op_get"]["reads_per_sec"],
            "speedup_x": r["speedup_x"],
            "scan_reads_per_sec": r["cells"]["scan"]["reads_per_sec"],
            "checker_ok": r["checker_ok"],
            "stale_read_clean": r["stale_read_clean"],
        })
        # the acceptance floor is part of the cell's meaning: a read path
        # slower than 5x the per-op path, or an unverified one, is a FAIL
        if (r["speedup_x"] < r["speedup_floor"] or not r["checker_ok"]
                or not r["stale_read_clean"]):
            sys.exit(1)
        return

    if args.values:
        r = run_values_bench(n=args.values_ops)
        with open("BENCH_VALUES.json", "w") as f:
            json.dump(r, f, indent=1)
        cell(r)
        out.write({
            "metric": "value_put_gb_per_sec",
            "value": r["put_gb_per_sec"],
            "unit": "GB/s",
            "writes_per_sec": r["writes_per_sec"],
            "read_gb_per_sec": r["read_gb_per_sec"],
            "device_gb_per_sec": r["device_gb_per_sec"],
            "gc_runs": r["cells"]["gc"]["gc_runs"],
            "post_gc_util": r["cells"]["gc"]["post_gc_util"],
            "values_ok": r["values_ok"],
        })
        # byte-inexact round trips make the GB/s numbers meaningless
        if not r["values_ok"]:
            sys.exit(1)
        return

    if args.fleet:
        r = run_fleet_bench(groups=args.fleet_groups)
        with open("BENCH_FLEET.json", "w") as f:
            json.dump(r, f, indent=1)
        cell(r)
        out.write({
            "metric": "fleet_aggregate_writes_per_sec",
            "value": r["aggregate_writes_per_sec"],
            "unit": "writes/s",
            "groups": r["groups"],
            "single_group": r["single_group"]["writes_per_sec"],
            "scaleout_x": r["scaleout_x"],
            "concurrent": r["concurrent"]["writes_per_sec"],
        })
        return

    if args.chaos is not None:
        r = run_chaos_soak(args.chaos, rounds=args.pipeline_rounds,
                           depth=max(2, args.pipeline_depth))
        with open("CHAOS_BENCH.json", "w") as f:
            json.dump(r, f, indent=1)
        cell(r)
        out.write({
            "metric": "chaos_soak_round_us",
            "clean": r["cells"]["clean"]["round_us"],
            "chaos": r["cells"]["chaos"]["round_us"],
            "slowdown": r["slowdown"],
            "dip_pct": r["dip_pct"],
            "events": r["cells"]["chaos"]["events_applied"],
        })
        return

    if args.pipeline:
        r = run_pipeline_compare(depth=args.pipeline_depth,
                                 rounds=args.pipeline_rounds,
                                 mix=args.mix if args.mix != "all" else "a")
        with open("PIPELINE_COMPARE.json", "w") as f:
            json.dump(r, f, indent=1)
        cell(r)
        # the stdout line stays scalar-only (the per-cell histograms live
        # in the JSON artifact)
        out.write({
            "metric": "pipeline_host_overhead_s",
            "sync": r["host_overhead_sync_s"],
            "pipelined": r["host_overhead_pipelined_s"],
            "meta_equal": r["meta_equal"],
            "latency_round_us": r["latency"],
        })
        if not r["meta_equal"]:
            sys.exit(1)
        return

    if args.mix == "latency":
        r = run_latency()
        cell(r)
        out.write(r)
        return

    mixes = MIXES if args.mix == "all" else (args.mix,)
    results = {}
    profile_recs = []
    for mix in mixes:
        r = run_mix(mix)
        results[mix] = r
        cell(r)
        err.write(r)
        if args.profile_out:
            # fusion-level accountability for the measured number: the op
            # census of the exact program just timed, plus the cost-model
            # pricing of its sparse chain against the measured round time
            # (lowering is host-side — the chip is not touched again)
            from hermes_tpu.obs import profile as prof

            profile_recs.append(prof.round_record(
                prof.op_census(_cfg(mix)), mix=mix,
                round_ms=round(r["round_us"] / 1e3, 3),
                writes_per_sec=r["writes_per_sec"]))

    if args.profile_out and profile_recs:
        from hermes_tpu.obs import profile as prof

        prof.export_profile(args.profile_out, profile_recs)

    if args.analyze:
        # invariant accountability next to the measured number: the
        # analyzer's verdict on the exact programs just timed (host-side
        # abstract tracing — the chip is not touched again)
        from hermes_tpu import analysis as ana

        reports = []
        for mix in mixes:
            for r in ana.analyze_config(_cfg(mix), engines=("batched",)):
                for f in r["findings"]:
                    f.engine = f"{mix}:{f.engine}"
                reports.append(r)
        ana.export_findings(args.analyze, reports)

    if args.mix == "all":
        # latency operating point at three scales (round-3 verdict item 7):
        # p50 - dispatch_floor isolates program latency from the tunneled
        # link handshake at each in-flight count
        for s in (256, 1024, 4096):
            rec = run_latency(n_sessions=s)
            rec["mix"] = f"latency_s{s}"
            results[rec["mix"]] = rec
            cell(rec)
            err.write(rec)
        # historical key: a copy, so its mix tag still reads "latency" (the
        # outage path emits {"mix": "latency", ...} — consumers key on it)
        results["latency"] = dict(results["latency_s1024"], mix="latency")
        with open("BENCH_MIXES.json", "w") as f:
            json.dump(results, f, indent=1)

    primary = results.get("a") or results[mixes[0]]
    line = {
        "metric": "committed_writes_per_sec",
        "value": primary["writes_per_sec"],
        "unit": "writes/s",
        "vs_baseline": round(primary["writes_per_sec"] / 1e7, 4),
    }
    if primary["mix"] != "a":
        # never let a non-primary mix masquerade as the driver's YCSB-A
        # metric: tag the stdout line so scrapers can tell them apart
        line["metric"] = f"committed_writes_per_sec_{primary['mix']}"
    cell(line)
    out.write(line)


if __name__ == "__main__":
    main()
