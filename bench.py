"""Benchmark: committed writes/sec of the Hermes protocol round.

Target (BASELINE.json:5): >=10M committed writes/sec aggregate on a v5e-8
(8 replicas, 1 chip = 1 replica).  This environment exposes ONE v5e chip, so
the bench runs the 8-replica configuration batched on that chip — every
replica's protocol work AND all 8x8 message traffic execute on the single
chip.  A real 8-chip mesh splits this work 8 ways (each chip applies each
write once instead of this chip applying it 8 times) and pays ICI instead of
on-chip copies, so the single-chip number lower-bounds the real-mesh
aggregate.

Runs the TPU-optimized round (core/faststep.py: packed-ts scatter-max
conflict resolution, lane compaction, cond-gated replay scan), scan-chunked
so one dispatch executes ROUNDS protocol rounds (SURVEY.md §7 M6).

Measurement protocol for this runtime (measured, see faststep.py header):
execution through the tunneled PJRT link is DEFERRED until the first
device-to-host readback — ``block_until_ready`` alone does not execute the
queued work — and after that first readback the session runs synchronously.
The first counter readback below therefore both drains the warmup chunk and
switches to honest timing for the measured loop.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} with
vs_baseline = value / 1e7 (the north-star aggregate target).
"""

import json
import sys
import time

import jax
import jax.numpy as jnp

ROUNDS = 50  # protocol rounds per dispatch
CHUNKS = 4  # measured dispatches
WARMUP_CHUNKS = 1


def main() -> None:
    from hermes_tpu.config import HermesConfig, WorkloadConfig
    from hermes_tpu.core import faststep as fst
    from hermes_tpu.stats import percentile_from_hist
    from hermes_tpu.workload import ycsb

    cfg = HermesConfig(
        n_replicas=8,
        n_keys=1 << 20,  # 1M keys (BASELINE.json:7)
        value_words=8,  # 32B values, the reference's typical small-value shape
        n_sessions=32768,  # in-flight ops per replica (tuned on-chip)
        replay_slots=256,
        ops_per_session=256,
        wrap_stream=True,  # stream cycles; write uids stay unique (config.py)
        device_stream=True,  # counter-hash op stream (no stream gathers)
        lane_budget_cfg=24576,
        read_unroll=2,  # local-read drain depth (reference read batching)
        rebroadcast_every=4,
        replay_scan_every=32,
        workload=WorkloadConfig(read_frac=0.5, seed=0),  # YCSB-A; metric counts writes
    )

    fs = jax.device_put(fst.init_fast_state(cfg))
    stream = jax.device_put(fst.prep_stream(ycsb.stub_stream(cfg)))
    chunk = fst.build_fast_scan(cfg, ROUNDS, donate=True)

    def counters(x):
        m = jax.device_get(x.meta)
        return int(m.n_write.sum() + m.n_rmw.sum())

    for c in range(WARMUP_CHUNKS):
        fs = chunk(fs, stream, fst.make_fast_ctl(cfg, c * ROUNDS))
    jax.block_until_ready(fs)
    c0 = counters(fs)  # drains warmup; switches the link to synchronous mode
    lat0 = jax.device_get(fs.meta.lat_hist).sum(axis=0)

    t0 = time.perf_counter()
    for c in range(WARMUP_CHUNKS, WARMUP_CHUNKS + CHUNKS):
        fs = chunk(fs, stream, fst.make_fast_ctl(cfg, c * ROUNDS))
    jax.block_until_ready(fs)
    t1 = time.perf_counter()

    measure = CHUNKS * ROUNDS
    commits = counters(fs) - c0
    wall = t1 - t0
    wps = commits / wall

    # p50 commit latency in protocol rounds -> microseconds via measured
    # round time (commit latency = 1 round for an uncontended write)
    hist = jax.device_get(fs.meta.lat_hist).sum(axis=0) - lat0
    p50_rounds = percentile_from_hist(hist, 0.5)
    p99_rounds = percentile_from_hist(hist, 0.99)
    step_us = wall / measure * 1e6

    meta = {
        "commits": commits,
        "rounds": measure,
        "wall_s": round(wall, 4),
        "round_us": round(step_us, 1),
        "p50_commit_rounds": p50_rounds,
        "p99_commit_rounds": p99_rounds,
        "p50_commit_us_est": round((p50_rounds + 1) * step_us, 1),
        "p99_commit_us_est": round((p99_rounds + 1) * step_us, 1),
        "platform": jax.devices()[0].platform,
        "device": getattr(jax.devices()[0], "device_kind", "?"),
        "replicas_on_chip": cfg.n_replicas,
        "rounds_per_dispatch": ROUNDS,
        "n_sessions": cfg.n_sessions,
        "lane_budget": cfg.lane_budget,
    }
    print(json.dumps(meta), file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "committed_writes_per_sec",
                "value": round(wps, 1),
                "unit": "writes/s",
                "vs_baseline": round(wps / 1e7, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
