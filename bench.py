"""Benchmark: committed writes/sec of the Hermes protocol step.

Target (BASELINE.json:5): >=10M committed writes/sec aggregate on a v5e-8
(8 replicas, 1 chip = 1 replica).  This environment exposes ONE v5e chip, so
the bench runs the 8-replica configuration batched on that chip — every
replica's kernel work AND all 8x8 message traffic execute on the single
chip, which lower-bounds the per-chip work of the real 8-chip mesh (the real
mesh splits this work 8 ways and pays ICI instead of on-chip copies).

The chip is reached through a tunneled PJRT link whose round-trip latency is
large and variable, so the measured loop is scan-chunked (SURVEY.md §7 M6):
``build_step_scan`` runs ROUNDS protocol rounds per dispatch and the host
touches the device a handful of times total.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} with
vs_baseline = value / 1e7 (the north-star aggregate target).
"""

import json
import sys
import time

import jax
import jax.numpy as jnp

ROUNDS = 100  # protocol rounds per dispatch
CHUNKS = 5  # measured dispatches
WARMUP_CHUNKS = 2


def main() -> None:
    from hermes_tpu.config import HermesConfig, WorkloadConfig
    from hermes_tpu.core import state as st, step as step_lib
    from hermes_tpu.workload import ycsb

    cfg = HermesConfig(
        n_replicas=8,
        n_keys=1 << 20,
        value_words=8,  # 32B values, the reference's typical small-value shape
        n_sessions=4096,
        replay_slots=256,
        ops_per_session=256,
        wrap_stream=True,  # stream cycles; uids stay unique (config.py)
        workload=WorkloadConfig(read_frac=0.5, seed=0),  # YCSB-A mix; metric counts writes
    )

    r = cfg.n_replicas
    rs = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (r,) + x.shape), st.init_replica_state(cfg)
    )
    rs = jax.device_put(rs)
    stream = jax.device_put(jax.tree.map(jnp.asarray, ycsb.make_streams(cfg)))

    chunk = step_lib.build_step_scan(cfg, ROUNDS, donate=True)

    def counters(x):
        m = jax.device_get(x.meta)
        return int(m.n_write.sum() + m.n_rmw.sum())

    for c in range(WARMUP_CHUNKS):
        rs = chunk(rs, stream, step_lib.make_ctl(cfg, c * ROUNDS))
    jax.block_until_ready(rs)
    c0 = counters(rs)
    lat0 = jax.device_get(rs.meta.lat_hist).sum(axis=0)

    t0 = time.perf_counter()
    for c in range(WARMUP_CHUNKS, WARMUP_CHUNKS + CHUNKS):
        rs = chunk(rs, stream, step_lib.make_ctl(cfg, c * ROUNDS))
    jax.block_until_ready(rs)
    t1 = time.perf_counter()

    measure = CHUNKS * ROUNDS
    commits = counters(rs) - c0
    wall = t1 - t0
    wps = commits / wall

    # p50 commit latency in steps -> microseconds via measured step time
    from hermes_tpu.stats import percentile_from_hist

    hist = jax.device_get(rs.meta.lat_hist).sum(axis=0) - lat0
    p50_steps = percentile_from_hist(hist, 0.5)
    step_us = wall / measure * 1e6

    meta = {
        "commits": commits,
        "steps": measure,
        "wall_s": round(wall, 4),
        "step_us": round(step_us, 1),
        "p50_commit_steps": p50_steps,
        "p50_commit_us_est": round((p50_steps + 1) * step_us, 1),
        "platform": jax.devices()[0].platform,
        "device": getattr(jax.devices()[0], "device_kind", "?"),
        "replicas_on_chip": cfg.n_replicas,
        "rounds_per_dispatch": ROUNDS,
    }
    print(json.dumps(meta), file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "committed_writes_per_sec",
                "value": round(wps, 1),
                "unit": "writes/s",
                "vs_baseline": round(wps / 1e7, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
